"""Multi-worker server plane tests (serve/frontend.py + serve/ipc.py).

The correctness bar for the SO_REUSEPORT + shared-memory-ring plane:

- responses BIT-IDENTICAL to the single-process path over every bucket
  and group family (the wire contract is `serve/wire.py format_response`
  fed by the same raw arrays on both planes);
- the HTTP edge cases the multi-process split makes riskier — pipelined
  keep-alive, oversized 413, malformed Content-Length, mid-body client
  disconnect — pinned against BOTH a 1-worker (single-process) and a
  2-worker (forked front ends) server;
- overload sheds fast 503s with Retry-After while admitted requests
  complete;
- SIGTERM drains: in-flight exchanges finish, children exit 0, the
  engine survives;
- a kill -9'd front end never wedges the ring (respawn re-attaches via
  the generation counters);
- the ring's lock/semaphore discipline holds under the PR 5 runtime lock
  sanitizer across seeded schedule perturbations.
"""

import contextlib
import dataclasses
import json
import os
import signal
import socket
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from mlops_tpu.config import ServeConfig, ServeConfigError
from mlops_tpu.serve.frontend import (
    _respawn,
    reuseport_socket,
    start_frontends,
)
from mlops_tpu.serve.ipc import RequestRing, RingService


@pytest.fixture(scope="module")
def engine(warm_engine):
    return warm_engine  # session-shared warmed engine (conftest)


@pytest.fixture(scope="module")
def prep_path(warm_engine, tmp_path_factory):
    path = tmp_path_factory.mktemp("frontend") / "preprocess.npz"
    warm_engine.bundle.preprocessor.save(path)
    return str(path)


# --------------------------------------------------------------- harness
@contextlib.contextmanager
def multi_worker_plane(
    engine,
    prep_path,
    workers=2,
    slots_small=8,
    slots_large=2,
    service_kwargs=None,
    trace=None,
    **cfg_kwargs,
):
    """The production topology with the engine half hosted in this
    process (exactly what `serve_multi_worker` builds, minus the bundle
    load): forked SO_REUSEPORT front ends + ring + RingService.
    ``trace`` (a TraceConfig) arms tracewire exactly like
    serve_multi_worker: shm tracing flag before fork, per-worker span
    recorders in the children."""
    cfg_kwargs.setdefault("max_batch", 64)
    cfg = ServeConfig(
        host="127.0.0.1",
        port=0,
        workers=workers,
        ring_slots_small=slots_small,
        ring_slots_large=slots_large,
        **cfg_kwargs,
    ).validate()
    ring = RequestRing(
        workers=workers,
        slots_small=slots_small,
        slots_large=slots_large,
        large_rows=cfg.max_batch,
    )
    if trace is not None and trace.enabled:
        os.makedirs(trace.dir, exist_ok=True)
        ring.set_tracing(True)
    placeholder = reuseport_socket(cfg.host, cfg.port)
    child_cfg = dataclasses.replace(cfg, port=placeholder.getsockname()[1])
    procs = start_frontends(child_cfg, ring, prep_path, trace)
    service = RingService(
        engine,
        ring,
        max_group=cfg.max_group,
        max_inflight=cfg.max_inflight,
        threads=cfg.max_workers,
        **(service_kwargs or {}),
    )
    service.start()
    ring.set_ready(True)
    _wait_accepting(child_cfg.port)
    try:
        yield child_cfg.port, ring, procs, service
    finally:
        ring.set_draining()
        ring.set_ready(False)
        for proc in procs:
            if proc.is_alive() and proc.pid:
                with contextlib.suppress(ProcessLookupError):
                    os.kill(proc.pid, signal.SIGTERM)
        for proc in procs:
            proc.join(timeout=15)
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=5)
        service.stop()
        placeholder.close()
        ring.close()


def _wait_accepting(port, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return
        except OSError:
            time.sleep(0.05)
    raise TimeoutError(f"no front end accepting on :{port}")


@contextlib.contextmanager
def single_process_server(engine, tracer=None, **cfg_kwargs):
    """The 1-worker baseline: the in-process HttpServer on a background
    event-loop thread, addressable through the same blocking-socket
    client as the multi-worker plane. ``tracer`` (a TraceRecorder) arms
    tracewire spans the way _serve's trace wiring would."""
    import asyncio

    from mlops_tpu.serve.server import HttpServer

    cfg_kwargs.setdefault("max_batch", 64)
    holder: dict = {}
    started = threading.Event()

    async def main():
        server = HttpServer(
            engine, ServeConfig(host="127.0.0.1", port=0, **cfg_kwargs)
        )
        server.tracer = tracer
        srv = await server.start()
        holder["port"] = srv.sockets[0].getsockname()[1]
        holder["stop"] = asyncio.Event()
        holder["loop"] = asyncio.get_running_loop()
        started.set()
        await holder["stop"].wait()
        srv.close()
        server.stop_telemetry()
        await srv.wait_closed()

    thread = threading.Thread(target=lambda: asyncio.run(main()), daemon=True)
    thread.start()
    assert started.wait(15), "single-process server did not start"
    try:
        yield holder["port"]
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        thread.join(timeout=10)


# --------------------------------------------------------------- client
def _recv_response(sock_file):
    status_line = sock_file.readline()
    if not status_line:
        return None
    status = int(status_line.split(b" ")[1])
    headers = {}
    while True:
        line = sock_file.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        name, _, value = line.decode("latin1").partition(":")
        headers[name.strip().lower()] = value.strip()
    body = sock_file.read(int(headers.get("content-length", 0)))
    return status, headers, body


def http_exchange(port, method, path, body=None, headers=None, close=True):
    data = b"" if body is None else json.dumps(body).encode()
    head = [f"{method} {path} HTTP/1.1", "host: t",
            f"content-length: {len(data)}"]
    for name, value in (headers or {}).items():
        head.append(f"{name}: {value}")
    if close:
        head.append("connection: close")
    raw = ("\r\n".join(head) + "\r\n\r\n").encode() + data
    with socket.create_connection(("127.0.0.1", port), timeout=30) as sock:
        sock.sendall(raw)
        with sock.makefile("rb") as f:
            return _recv_response(f)


def predict(port, records):
    status, headers, body = http_exchange(port, "POST", "/predict", records)
    return status, headers, (json.loads(body) if body else None)


# ---------------------------------------------------------------- parity
def test_multiworker_responses_bit_identical_to_single_process(
    engine, prep_path, sample_request
):
    """Every bucket family (empty, 1, 3->8, 8, 20->64, 64 rows) and the
    group path must produce byte-for-byte the single-process response."""
    sizes = [0, 1, 3, 8, 20, 64]
    with multi_worker_plane(engine, prep_path, workers=2) as (port, *_):
        for n in sizes:
            records = sample_request * n
            status, _, multi = predict(port, records)
            assert status == 200, multi
            solo = engine.predict_records(records)
            assert multi == json.loads(json.dumps(solo)), f"size {n} differs"


@pytest.mark.slow  # 24-thread burst + fresh plane: CI's parallel job runs it
def test_multiworker_grouped_path_bit_identical(engine, prep_path, sample_request):
    """Concurrent batch-1 requests with DISTINCT payloads coalesce into
    grouped dispatches engine-side; each response must equal the solo
    single-process response for its own record (no cross-wiring, no
    grouping artifacts)."""
    base = dict(sample_request[0])
    variants = []
    for i in range(24):
        record = dict(base)
        record["credit_limit"] = 1000.0 + 250.0 * i
        record["age"] = 20 + i
        variants.append(record)
    expected = [engine.predict_records([r]) for r in variants]

    # 2 workers x 16 small slots: the 24-request burst always fits the
    # admission queues (this test pins grouping parity, not shedding).
    with multi_worker_plane(
        engine, prep_path, workers=2, slots_small=16
    ) as (port, *_):
        results: list = [None] * len(variants)

        def call(i):
            status, _, payload = predict(port, [variants[i]])
            results[i] = (status, payload)

        threads = [
            threading.Thread(target=call, args=(i,))
            for i in range(len(variants))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    for i, (status, payload) in enumerate(results):
        assert status == 200
        assert payload == json.loads(json.dumps(expected[i])), f"req {i}"


# ----------------------------------------------------- HTTP edge cases
def _edge_case_suite(port):
    # 1) pipelined keep-alive: three requests written back-to-back before
    # any response is read; three well-formed responses come back in
    # order on the one connection.
    body = json.dumps([{}]).encode()
    one = (
        b"POST /predict HTTP/1.1\r\nhost: t\r\n"
        b"content-type: application/json\r\n"
        + f"content-length: {len(body)}\r\n\r\n".encode()
        + body
    )
    with socket.create_connection(("127.0.0.1", port), timeout=30) as sock:
        sock.sendall(one * 3)
        with sock.makefile("rb") as f:
            for _ in range(3):
                status, headers, payload = _recv_response(f)
                assert status == 200
                assert len(json.loads(payload)["predictions"]) == 1

    # 2) oversized declared body: 413 before the server ever reads it.
    with socket.create_connection(("127.0.0.1", port), timeout=30) as sock:
        sock.sendall(
            b"POST /predict HTTP/1.1\r\nhost: t\r\n"
            b"content-length: 999999999\r\n\r\n"
        )
        with sock.makefile("rb") as f:
            status, _, payload = _recv_response(f)
    assert status == 413
    assert b"exceeds" in payload

    # 3) malformed Content-Length: 400, connection closed, no crash —
    # non-numeric, negative, and the Python-only int() spellings RFC 9110
    # forbids ('+5', '1_0' would parse but disagree with conformant
    # intermediaries: request-smuggling surface).
    for bad_length in (b"abc", b"-1", b"+5", b"1_0"):
        with socket.create_connection(("127.0.0.1", port), timeout=30) as sock:
            sock.sendall(
                b"POST /predict HTTP/1.1\r\nhost: t\r\ncontent-length: "
                + bad_length + b"\r\n\r\n"
            )
            with sock.makefile("rb") as f:
                status, _, _ = _recv_response(f)
        assert status == 400, bad_length

    # 3c) Transfer-Encoding is unsupported: reject AND close — reading
    # the chunk framing as a next pipelined request would desync the
    # connection (request-smuggling class).
    with socket.create_connection(("127.0.0.1", port), timeout=30) as sock:
        sock.sendall(
            b"POST /predict HTTP/1.1\r\nhost: t\r\n"
            b"transfer-encoding: chunked\r\n\r\n"
            b"5\r\nAAAAA\r\n0\r\n\r\n"
        )
        with sock.makefile("rb") as f:
            status, _, _ = _recv_response(f)
            assert status == 400
            assert f.readline() == b"", "connection must close, not re-parse"

    # 3d) duplicate Content-Length lines: 400 (last-wins parsing would
    # disagree with conformant intermediaries — smuggling class).
    with socket.create_connection(("127.0.0.1", port), timeout=30) as sock:
        sock.sendall(
            b"POST /predict HTTP/1.1\r\nhost: t\r\n"
            b"content-length: 4\r\ncontent-length: 30\r\n\r\n[{}]"
        )
        with sock.makefile("rb") as f:
            status, _, _ = _recv_response(f)
    assert status == 400

    # 4) mid-body client disconnect: declared 100 bytes, sent 10, then a
    # hard close — the server must shrug it off and keep serving.
    with socket.create_connection(("127.0.0.1", port), timeout=30) as sock:
        sock.sendall(
            b"POST /predict HTTP/1.1\r\nhost: t\r\n"
            b"content-length: 100\r\n\r\n0123456789"
        )
    status, _, payload = predict(port, [{}])
    assert status == 200 and len(payload["predictions"]) == 1


def test_http_edge_cases_single_process(engine):
    with single_process_server(engine) as port:
        _edge_case_suite(port)


def test_http_edge_cases_two_workers(engine, prep_path):
    with multi_worker_plane(engine, prep_path, workers=2) as (port, *_):
        _edge_case_suite(port)


# ------------------------------------------------------------- shedding
class _SlowStubEngine:
    """Engine-API stub with a controllable dispatch latency — jax-free,
    deterministic, lets the shed/drain tests hold slots in flight."""

    ready = True
    max_bucket = 64
    supports_grouping = False
    monitor_accumulating = False

    class _Handle:
        def __init__(self, n):
            self.n = n

        def start_copy(self):
            pass

    def __init__(self, delay_s: float):
        self.delay_s = delay_s

    def dispatch_arrays(self, cat, num):
        return self._Handle(cat.shape[0])

    def fetch_arrays_raw(self, handle):
        time.sleep(self.delay_s)
        n = handle.n
        return (
            np.full(n, 0.25, float),
            np.zeros(n, float),
            np.zeros(23, float),
        )


def test_overload_burst_sheds_fast_503_with_retry_after(prep_path):
    """One small slot per worker + a slow engine: a concurrent burst gets
    some admitted 200s and FAST 503s with the Retry-After contract for
    the rest; /metrics records the sheds."""
    stub = _SlowStubEngine(delay_s=0.5)
    with multi_worker_plane(
        stub, prep_path, workers=1, slots_small=1, slots_large=1
    ) as (port, ring, _, _svc):
        results = []
        lock = threading.Lock()

        def call():
            t0 = time.perf_counter()
            status, headers, payload = predict(port, [{}])
            with lock:
                results.append(
                    (status, headers, (time.perf_counter() - t0))
                )

        threads = [threading.Thread(target=call) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        statuses = [s for s, _, _ in results]
        assert statuses.count(200) >= 1
        sheds = [r for r in results if r[0] == 503]
        assert sheds, f"no sheds in {statuses}"
        for status, headers, elapsed in sheds:
            assert headers.get("retry-after") == "1"
            # FAST: a shed must not wait out the slow dispatch.
            assert elapsed < 0.45, f"shed took {elapsed:.3f}s"
        assert int(ring.shed.sum()) == len(sheds)
        status, _, body = http_exchange(None or port, "GET", "/metrics")
        assert status == 200
        assert b"mlops_tpu_shed_total" in body


def test_brownout_demotes_default_class_before_shedding(prep_path):
    """Overload with SLO routing armed (ISSUE 19): as the slot partition
    crosses the governor's demote depth, admitted default-class requests
    demote to the cheap class — counted in the per-worker shm demotion
    cells — BEFORE the partition exhausts into 503s. Brownout spends
    fidelity first; the shed path only fires once the partition (the
    cheapest tier's own capacity) is saturated."""
    stub = _SlowStubEngine(delay_s=0.5)
    with multi_worker_plane(
        stub,
        prep_path,
        workers=1,
        slots_small=8,
        slots_large=2,
        tier_routing=True,
    ) as (port, ring, _, _svc):
        results = []
        lock = threading.Lock()

        def call():
            status, headers, _ = predict(port, [{}])
            with lock:
                results.append((status, headers))

        threads = [threading.Thread(target=call) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        statuses = [s for s, _ in results]
        # brownout-over-shed: no new failure modes, still bounded
        assert set(statuses) <= {200, 503}, statuses
        assert statuses.count(200) >= 8, statuses
        assert statuses.count(503) >= 1, statuses
        # Demotions were counted: reaching 100% occupancy (the shed
        # condition) necessarily crossed the 75% demote depth first, so
        # the governor demoted admitted traffic before the first 503.
        assert int(ring.tier_demote.sum()) >= 1
        assert int(ring.brownout_demote.sum()) == int(
            ring.tier_demote.sum()
        )
        status, _, body = http_exchange(port, "GET", "/metrics")
        assert status == 200
        text = body.decode()
        assert "mlops_tpu_tier_demotions_total" in text
        assert "mlops_tpu_brownout_demote_total" in text
        assert 'mlops_tpu_tier_requests_total{tier="quant"}' in text


def test_explicit_accurate_class_is_never_demoted(prep_path):
    """The accurate-class escape hatch: even under full brownout, a
    request pinning ``x-slo-class: accurate`` keeps its class (the shm
    slot tag stays SLO_ACCURATE and no demotion is counted for it)."""
    stub = _SlowStubEngine(delay_s=0.3)
    with multi_worker_plane(
        stub,
        prep_path,
        workers=1,
        slots_small=2,
        slots_large=1,
        tier_routing=True,
    ) as (port, ring, _, _svc):
        # Saturate the 3-slot partition with default-class traffic so
        # the governor is active, then pin one accurate request.
        results = []
        lock = threading.Lock()

        def call(headers=None):
            status, _, _ = http_exchange(
                port, "POST", "/predict", body=[{}], headers=headers
            )
            with lock:
                results.append(status)

        filler = [threading.Thread(target=call) for _ in range(4)]
        for t in filler:
            t.start()
        time.sleep(0.1)
        before = int(ring.tier_demote.sum())
        pinned = threading.Thread(
            target=call, args=({"x-slo-class": "accurate"},)
        )
        pinned.start()
        pinned.join(timeout=30)
        for t in filler:
            t.join(timeout=30)
        # The pinned request never demoted: the demotion counter's growth
        # after it was issued is attributable only to default traffic,
        # and the slot tags only ever carried {default, cheap, accurate}.
        assert int(ring.tier_demote.sum()) >= before
        assert set(results) <= {200, 503}


# ------------------------------------------------------------- /metrics
def test_multiworker_metrics_show_every_worker_and_monitor_aggregate(
    engine, prep_path, sample_request
):
    with multi_worker_plane(engine, prep_path, workers=2) as (
        port, ring, _, service,
    ):
        for _ in range(4):
            assert predict(port, sample_request)[0] == 200
        # Engine-process single-flight aggregate write (the telemetry
        # loop's job; driven directly here to avoid a cadence wait).
        ring.write_monitor(engine.monitor_snapshot())
        status, _, body = http_exchange(port, "GET", "/metrics")
        text = body.decode()
    assert status == 200
    for worker in (0, 1):
        assert (
            f'mlops_tpu_ring_depth{{worker="{worker}",class="small",'
            'tenant="default"}' in text
        )
        assert (
            f'mlops_tpu_shed_total{{worker="{worker}",class="small",'
            'tenant="default"}' in text
        )
    # request counters carry worker labels (at least one worker served)
    assert 'route="/predict",status="200",worker="' in text
    assert "mlops_tpu_rows_scored_total" in text
    assert "mlops_tpu_feature_drift_score" in text
    assert "mlops_tpu_monitor_fetches_total" in text


# ------------------------------------------------------------------ drain
def test_sigterm_drains_inflight_and_children_exit_zero(prep_path):
    stub = _SlowStubEngine(delay_s=0.8)
    with multi_worker_plane(
        stub, prep_path, workers=2, request_timeout_s=30.0
    ) as (port, ring, procs, _svc):
        result = {}

        def call():
            result["r"] = predict(port, [{}])

        thread = threading.Thread(target=call)
        thread.start()
        time.sleep(0.25)  # let the exchange reach the engine
        for proc in procs:
            os.kill(proc.pid, signal.SIGTERM)
        thread.join(timeout=30)
        status, _, payload = result["r"]
        assert status == 200
        assert payload["predictions"] == [0.25]
        for proc in procs:
            proc.join(timeout=15)
        assert [p.exitcode for p in procs] == [0, 0]


@pytest.mark.slow  # retry/poll loops: CI's parallel job runs it
def test_killed_frontend_never_wedges_ring_and_respawns(
    engine, prep_path, sample_request
):
    """kill -9 a front end mid-flight: the engine keeps serving the other
    worker, and a respawned process re-attaches to the partition (the
    generation counters make the dead incarnation's completions stale)."""
    with multi_worker_plane(engine, prep_path, workers=2) as (
        port, ring, procs, _svc,
    ):
        assert predict(port, sample_request)[0] == 200
        os.kill(procs[0].pid, signal.SIGKILL)
        procs[0].join(timeout=10)
        # The surviving worker answers (the dead listener's socket is
        # gone, so the kernel routes new connections to the live one).
        deadline = time.time() + 15
        served = False
        while time.time() < deadline and not served:
            try:
                served = predict(port, sample_request)[0] == 200
            except OSError:
                time.sleep(0.1)
        assert served, "surviving worker did not serve"
        # Respawn worker 0 — the supervisor's move, done by hand here.
        child_cfg = ServeConfig(
            host="127.0.0.1", port=port, workers=2, max_batch=64
        )
        procs[0] = _respawn(child_cfg, ring, prep_path, 0)
        _wait_accepting(port)
        for _ in range(6):  # both listeners live; hashing hits each soon
            assert predict(port, sample_request)[0] == 200


@pytest.mark.slow  # in-flight kill -9 + respawn choreography
def test_respawn_quarantines_inflight_slots_until_engine_answers(prep_path):
    """A front end killed -9 with a request IN FLIGHT leaves its slot
    busy in shm. The respawned incarnation must QUARANTINE that slot (the
    engine may still write its slab) and only reuse it after the engine's
    completion arrives — reclaiming early would let the dead request's
    response scribble over a live one."""
    stub = _SlowStubEngine(delay_s=1.2)
    with multi_worker_plane(
        stub, prep_path, workers=1, slots_small=1, slots_large=1,
        request_timeout_s=30.0,
    ) as (port, ring, procs, _svc):
        def doomed_call():
            # The worker dies mid-request: whatever shape the connection
            # drop takes (reset, empty read, half a response) is the
            # expected outcome here, not a failure.
            with contextlib.suppress(Exception):
                predict(port, [{}])

        threading.Thread(target=doomed_call, daemon=True).start()
        deadline = time.time() + 5
        while time.time() < deadline and not int(ring.slot_busy.sum()):
            time.sleep(0.02)
        assert int(ring.slot_busy.sum()) == 1, "request never reached the ring"
        os.kill(procs[0].pid, signal.SIGKILL)
        procs[0].join(timeout=10)
        # The busy flag SURVIVES the crash — that is the quarantine input.
        assert int(ring.slot_busy.sum()) == 1
        child_cfg = ServeConfig(
            host="127.0.0.1", port=port, workers=1, max_batch=64
        )
        procs[0] = _respawn(child_cfg, ring, prep_path, 0)
        _wait_accepting(port)
        # While quarantined, the small slot is NOT claimable: a new small
        # request overflows into the large slab and still succeeds.
        status, _, payload = predict(port, [{}])
        assert status == 200 and payload["predictions"] == [0.25]
        # The engine's completion for the dead request drains quarantine.
        deadline = time.time() + 10
        while time.time() < deadline and int(ring.slot_busy.sum()):
            time.sleep(0.05)
        assert int(ring.slot_busy.sum()) == 0, "quarantine never drained"
        # Both slots free again: two concurrent requests both admit.
        results: list = [None, None]
        threads = [
            threading.Thread(
                target=lambda i=i: results.__setitem__(i, predict(port, [{}]))
            )
            for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert [r[0] for r in results] == [200, 200]


# ------------------------------------------------- slot accounting (unit)
def test_abandon_after_zombie_release_is_a_noop():
    """`asyncio.wait_for` cancels the deadline future and yields to the
    loop before TimeoutError reaches the handler; if the completion lands
    in that window, `on_doorbell`'s zombie path releases the slot first.
    The late `abandon()` must then do nothing — releasing again would put
    the slot on the free list twice (two requests sharing one slab) and
    underflow the inflight gauge."""
    import asyncio

    from mlops_tpu.schema import SCHEMA
    from mlops_tpu.serve.ipc import RingClient

    async def scenario():
        ring = RequestRing(
            workers=1, slots_small=2, slots_large=1, large_rows=8
        )
        try:
            client = RingClient(ring, 0)
            slot = client.claim(1)
            cat = np.zeros((1, SCHEMA.num_categorical), np.int32)
            num = np.zeros((1, SCHEMA.num_numeric), np.float32)
            future = client.submit(slot, cat, num)
            future.cancel()  # the deadline fired mid-wait_for
            # ...and the engine's completion lands in the cancellation
            # window, before the TimeoutError handler runs:
            gen = int(ring.slot_gen[slot])
            ring.resp_status[slot] = 0
            ring.resp_gen[slot] = gen
            ring.push_completion(slot, gen)
            ring.worker_doorbells[0].ring(1)  # publish the credit
            client.on_doorbell()  # zombie path releases the slot
            free = sum(len(f) for f in client._free)
            inflight = int(ring.inflight.sum())
            assert inflight == 0
            client.abandon(slot)  # the late TimeoutError handler
            assert sum(len(f) for f in client._free) == free, "double free"
            assert int(ring.inflight.sum()) == inflight, "gauge underflow"
        finally:
            ring.close()

    asyncio.run(scenario())


def test_respawned_client_counts_quarantined_slots_as_inflight():
    """The ring_depth gauge must not undercount across a worker crash: a
    respawned incarnation starts its inflight gauge at the quarantined
    (inherited-busy) slot count, and the quarantine drain decrements it
    as the engine's completions free each slot."""
    from mlops_tpu.serve.ipc import LARGE, SMALL, RingClient

    ring = RequestRing(workers=1, slots_small=2, slots_large=1, large_rows=8)
    try:
        small, _ = ring.worker_slots(0)
        busy = small[0]
        ring.slot_busy[busy] = 1  # the dead incarnation's in-flight slot
        # The dead incarnation also had requests PARKED (engine outage):
        # their decrements died with its event loop, so the respawned
        # client must zero the cell — not report phantom parked requests
        # forever (ISSUE 11 review finding).
        ring.parked[0] = 3
        # Worst-case ordering: the engine answered (stale generation) and
        # the DEAD incarnation drained the doorbell credit before dying —
        # the respawned client must seed its credit from the entries
        # already queued, or the quarantine would never drain.
        ring.push_completion(busy, int(ring.slot_gen[busy]))
        ring.worker_doorbells[0].ring(1)
        ring.worker_doorbells[0].drain()  # credit died with the worker
        client = RingClient(ring, 0)
        assert int(ring.inflight[0, 0, SMALL]) == 1
        assert int(ring.inflight[0, 0, LARGE]) == 0
        assert int(ring.parked[0]) == 0, "phantom parked gauge survived"
        assert client._credit == [1]  # one cell per engine replica
        client.on_doorbell()
        assert int(ring.inflight[0, 0, SMALL]) == 0
        assert busy in client._free[SMALL]
    finally:
        ring.close()


# ------------------------------------------------ survivable engine (11)
def test_engine_reattach_replays_busy_slot_bit_identically(
    engine, sample_request
):
    """ISSUE 11 tentpole correctness: a slot whose descriptor the dead
    engine POPPED but never answered (busy in shm, absent from the sub
    queue) is replayed by the respawned engine's re-attach — and the
    replayed answer is bit-identical to an uninterrupted run, because the
    slab holds the full pre-encoded input and packed predict is pure."""
    import asyncio
    import json as _json

    from mlops_tpu.schema import records_to_columns
    from mlops_tpu.serve.ipc import RingClient
    from mlops_tpu.serve.wire import RESP_OK, format_response

    expected = engine.predict_records(sample_request)

    async def scenario():
        ring = RequestRing(
            workers=1, slots_small=2, slots_large=1, large_rows=8
        )
        try:
            client = RingClient(ring, 0)
            ds = engine.bundle.preprocessor.encode(
                records_to_columns(sample_request)
            )
            slot = client.claim(len(sample_request))
            future = client.submit(slot, ds.cat_ids, ds.numeric)
            # Simulate the kill -9 window: the dead engine popped the
            # descriptor (tail advanced past it) and died mid-batch.
            popped = ring.pop_submissions()
            assert [s for s, _ in popped] == [slot]
            assert int(ring.slot_busy[slot]) == 1
            service = RingService(engine, ring, max_inflight=2, threads=2)
            try:
                stats = service.reattach()
            finally:
                service.stop()
            assert stats["incarnation"] == 1
            assert stats["replayed_slots"] == 1
            assert stats["replay_rows"] == len(sample_request)
            client.on_doorbell()  # the re-attach flush credited the entry
            assert future.done() and int(future.result()) == RESP_OK
            pred, out, drift = client.response_arrays(slot)
            got = format_response(
                np.array(pred), np.array(out), np.array(drift)
            )
            client.release(slot)
            assert got == _json.loads(_json.dumps(expected))
            assert int(ring.slot_busy.sum()) == 0
        finally:
            ring.close()

    asyncio.run(scenario())


def test_dead_incarnation_completion_is_dropped_not_double_served():
    """A completion a dead engine incarnation left behind must be DROPPED
    by the incarnation guard (nothing about a process that died mid-batch
    is trusted) — the replay's fresh completion, stamped with the live
    incarnation, is what resolves the future, exactly once."""
    import asyncio

    from mlops_tpu.schema import SCHEMA
    from mlops_tpu.serve.ipc import RingClient
    from mlops_tpu.serve.metrics import ENG_INCARNATION

    async def scenario():
        ring = RequestRing(
            workers=1, slots_small=2, slots_large=1, large_rows=8
        )
        try:
            ring.eng_vals[0, ENG_INCARNATION] = 1  # incarnation 1 is live
            client = RingClient(ring, 0)
            slot = client.claim(1)
            cat = np.zeros((1, SCHEMA.num_categorical), np.int32)
            num = np.zeros((1, SCHEMA.num_numeric), np.float32)
            future = client.submit(slot, cat, num)
            gen = int(ring.slot_gen[slot])
            # Incarnation 1 answered into the slab and queued the
            # completion... then got kill -9'd; the supervisor respawned
            # and the replacement bumped the incarnation word.
            ring.resp_status[slot] = 0
            ring.resp_incarnation[slot] = 1
            ring.resp_gen[slot] = gen
            ring.push_completion(slot, gen)
            ring.eng_vals[0, ENG_INCARNATION] = 2
            ring.worker_doorbells[0].ring(1)
            client.on_doorbell()
            assert not future.done(), (
                "a dead incarnation's completion was served"
            )
            # The replay (incarnation 2) re-answers the same (slot, gen).
            ring.resp_incarnation[slot] = 2
            ring.push_completion(slot, gen)
            ring.worker_doorbells[0].ring(1)
            client.on_doorbell()
            assert future.done() and int(future.result()) == 0
            client.release(slot)
            assert int(ring.inflight.sum()) == 0
        finally:
            ring.close()

    asyncio.run(scenario())


def test_duplicate_completion_across_respawn_is_not_double_released():
    """Replay can duplicate a completion the dead incarnation had already
    queued (its entry consumes a flush credit after the replay re-stamped
    the slot). The FIRST pop resolves the future; the duplicate must be a
    no-op — the awaiting handler owns the release, and releasing again
    would put the slot on the free list twice."""
    import asyncio

    from mlops_tpu.schema import SCHEMA
    from mlops_tpu.serve.ipc import RingClient

    async def scenario():
        ring = RequestRing(
            workers=1, slots_small=2, slots_large=1, large_rows=8
        )
        try:
            client = RingClient(ring, 0)
            slot = client.claim(1)
            cat = np.zeros((1, SCHEMA.num_categorical), np.int32)
            num = np.zeros((1, SCHEMA.num_numeric), np.float32)
            future = client.submit(slot, cat, num)
            gen = int(ring.slot_gen[slot])
            ring.resp_status[slot] = 0
            ring.resp_gen[slot] = gen  # incarnation 0 == live word: trusted
            ring.push_completion(slot, gen)
            ring.push_completion(slot, gen)  # the replay's duplicate
            ring.worker_doorbells[0].ring(2)
            client.on_doorbell()
            assert future.done() and int(future.result()) == 0
            free = sum(len(f) for f in client._free)
            inflight = int(ring.inflight.sum())
            assert inflight == 1, "slot must stay held by the handler"
            client.release(slot)  # the handler's release — exactly once
            assert sum(len(f) for f in client._free) == free + 1
            assert int(ring.inflight.sum()) == 0
        finally:
            ring.close()

    asyncio.run(scenario())


def test_brownout_shed_advertises_respawn_eta_and_parks_admissions(
    prep_path,
):
    """Engine-outage admission contract (ISSUE 11): while the engine is
    down, admissions PARK against the slot partition (the parked gauge
    counts them); once the partition is full, sheds become BROWNOUT 503s
    whose Retry-After advertises the respawn ETA and which count in
    brownout_shed_total — and /metrics exports the whole block."""
    from mlops_tpu.serve.metrics import ENG_DOWN_SINCE

    stub = _SlowStubEngine(delay_s=2.5)
    with multi_worker_plane(
        stub, prep_path, workers=1, slots_small=1, slots_large=1,
        engine_respawn_eta_s=7.0, request_timeout_s=30.0,
    ) as (port, ring, _, _svc):
        # The supervisor's detect-time moves: readiness drops and the
        # outage start is stamped (the stub RingService keeps running,
        # standing in for the respawned engine's replay).
        ring.set_ready(False)
        ring.eng_vals[0, ENG_DOWN_SINCE] = time.monotonic()
        results: list = [None, None]
        threads = [
            threading.Thread(
                target=lambda i=i: results.__setitem__(i, predict(port, [{}]))
            )
            for i in range(2)
        ]
        for t in threads:
            t.start()
        deadline = time.time() + 5
        while time.time() < deadline and int(ring.parked.sum()) < 2:
            time.sleep(0.02)
        assert int(ring.parked.sum()) == 2, "admissions did not park"
        status, _, body = http_exchange(port, "GET", "/metrics")
        assert status == 200
        text = body.decode()
        assert "mlops_tpu_parked_requests 2" in text
        assert "mlops_tpu_engine_respawn_total" in text
        assert "mlops_tpu_replayed_slots_total" in text
        assert "mlops_tpu_monitor_rows_lost_total" in text
        # Partition full + engine down => brownout 503 with the ETA.
        status, headers, payload = predict(port, [{}])
        assert status == 503, payload
        retry_after = int(headers["retry-after"])
        assert 1 <= retry_after <= 7
        assert "restarting" in str(payload)
        assert int(ring.brownout_shed.sum()) == 1
        for t in threads:
            t.join(timeout=30)
        # Parked admissions were answered (the stand-in engine replayed
        # them), not 504'd: budget never expired.
        assert [r[0] for r in results] == [200, 200]
        assert int(ring.parked.sum()) == 0
        ring.set_ready(True)


def test_survivability_series_zero_baseline_on_single_process_plane():
    """The single-process render exports the same survivability series
    names at a structural zero baseline — scrapes stay plane-portable and
    the chaos smoke's monotonicity check covers them everywhere."""
    from mlops_tpu.serve.metrics import ServingMetrics

    text = ServingMetrics().render()
    for series in (
        "mlops_tpu_engine_respawn_total 0",
        "mlops_tpu_replayed_slots_total 0",
        "mlops_tpu_monitor_rows_lost_total 0",
        "mlops_tpu_parked_requests 0",
        "mlops_tpu_brownout_shed_total 0",
        "mlops_tpu_engine_incarnation 0",
    ):
        assert series in text, series


@pytest.mark.slow  # boots the real CLI plane twice across an engine kill
def test_engine_kill9_is_survivable_brownout_on_real_plane(
    tiny_pipeline, tmp_path
):
    """The deployed-shape seeded faultline proof (ISSUE 11 acceptance):
    kill -9 the ENGINE process of a live 2-worker plane with a request
    held in flight by a seeded dispatch stall. The supervisor respawns
    the engine (warm from the AOT cache), the replacement re-attaches and
    REPLAYS the busy slot, and the parked request answers 200 with a body
    bit-identical to the pre-kill response — 504 never fires because the
    budget holds, and /metrics shows the respawn + replay counters."""
    import json as _json
    import re
    import subprocess
    import sys

    config, result = tiny_pipeline
    plan = tmp_path / "plan.toml"
    # Seeded stalls: the first TWO dispatches of each engine process hang
    # 2 s. Fire 1 is absorbed by the pre-kill reference request; fire 2
    # holds the kill victim in the engine — guaranteeing a busy, popped,
    # unanswered slot at kill time. The respawned engine's fresh counters
    # stall its replay dispatch the same way, proving parked requests
    # ride out a slow replay too.
    plan.write_text(
        'seed = 11\n[[fault]]\npoint = "serve.engine.dispatch*"\n'
        'mode = "delay"\ndelay_s = 2.0\nmax_fires = 2\n'
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MLOPS_TPU_FAULTS"] = str(plan)
    repo_root = str(Path(__file__).resolve().parent.parent)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH", "")) if p
    )
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    server = subprocess.Popen(
        [
            sys.executable, "-m", "mlops_tpu", "serve", "--workers", "2",
            "serve.host=127.0.0.1", f"serve.port={port}",
            f"serve.model_directory={result.bundle_dir}",
            "serve.warmup_batch_sizes=1,8", "serve.max_batch=8",
            "serve.request_timeout_s=90",
            f"cache.dir={tmp_path / 'cache'}",
            "serve.drain_deadline_s=8", "serve.zygote_join_deadline_s=10",
            "serve.engine_zygote_join_s=16",
        ],
        cwd=str(tmp_path), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    log_lines: list[str] = []
    pump = threading.Thread(
        target=lambda: log_lines.extend(iter(server.stdout.readline, "")),
        daemon=True,
    )
    pump.start()
    try:
        deadline = time.time() + 420
        ready = False
        while time.time() < deadline and not ready:
            assert server.poll() is None, "\n".join(log_lines[-40:])
            try:
                status, _, _ = http_exchange(port, "GET", "/healthz/ready")
                ready = status == 200
            except OSError:
                pass
            if not ready:
                time.sleep(0.5)
        assert ready, "plane never became ready"
        # Pre-kill reference response (absorbs the first seeded stall).
        status, _, expected = predict(port, [{"credit_limit": 9000}])
        assert status == 200
        engine_line = next(
            line for line in log_lines if "engine pid" in line
        )
        engine_pid = int(re.search(r"engine pid (\d+)", engine_line).group(1))

        inflight: dict = {}

        def stalled_call():
            t0 = time.perf_counter()
            s_, _, payload = predict(port, [{"credit_limit": 9000}])
            inflight["result"] = (s_, payload, time.perf_counter() - t0)

        # The kill victim: submitted, popped, held by the seeded stall —
        # then the engine dies under it. The replay must answer it.
        t = threading.Thread(target=stalled_call)
        t.start()
        time.sleep(0.25)  # let it reach the engine
        os.kill(engine_pid, signal.SIGKILL)
        # A second request ADMITTED DURING the outage parks on its
        # deadline budget and is answered once the replacement attaches.
        parked: dict = {}

        def parked_call():
            s_, _, payload = predict(port, [{"credit_limit": 9000}])
            parked["result"] = (s_, payload)

        t2 = threading.Thread(target=parked_call)
        t2.start()
        t.join(timeout=180)
        t2.join(timeout=180)
        assert not t.is_alive() and not t2.is_alive(), "parked call hung"
        status, payload, elapsed = inflight["result"]
        assert status == 200, (status, payload)
        # Bit-identical across the respawn: same AOT artifacts, same
        # pre-encoded slab input, pure packed predict.
        assert payload == expected
        assert elapsed > 1.0, "the kill victim never actually parked"
        assert parked["result"][0] == 200
        assert parked["result"][1] == expected
        deadline = time.time() + 30
        while time.time() < deadline:
            status, _, body = http_exchange(port, "GET", "/metrics")
            if status == 200 and b"mlops_tpu_engine_respawn_total 1" in body:
                break
            time.sleep(0.5)
        assert b"mlops_tpu_engine_respawn_total 1" in body
        assert re.search(rb"mlops_tpu_replayed_slots_total [1-9]", body), (
            body.decode()
        )
        assert b"mlops_tpu_engine_incarnation 2" in body
        server.send_signal(signal.SIGTERM)
        rc = server.wait(timeout=90)
        pump.join(timeout=10)
        log = "\n".join(log_lines)
        assert rc == 0, log[-3000:]
        assert "drained" in log
    finally:
        if server.poll() is None:
            server.kill()
            server.wait(timeout=10)
    expected_json = _json.dumps(expected, sort_keys=True)
    assert _json.dumps(inflight["result"][1], sort_keys=True) == expected_json


# ---------------------------------------------------------- lock hygiene
# Seed 0 stays in the serial tier-1 gate; the full 3-seed sweep (the
# acceptance bar) rides CI's parallel job like the other seeded stress
# suites — one plane spin-up per seed is what keeps them off the 870 s
# serial budget.
@pytest.mark.parametrize(
    "seed",
    [0, pytest.param(1, marks=pytest.mark.slow),
     pytest.param(2, marks=pytest.mark.slow)],
)
def test_ring_lock_discipline_under_perturbed_schedules(
    engine, prep_path, sample_request, seed
):
    """The PR 5 runtime sanitizer over the ring service + engine with
    seeded schedule perturbation: zero order violations, and responses
    stay bit-identical to the unperturbed single-process path."""
    from mlops_tpu.analysis.lockcheck import instrument_locks

    expected = engine.predict_records(sample_request)
    # 16 slots per worker: SO_REUSEPORT hashing can land most of the 12
    # connections on one worker, and a shed 503 here would fail the
    # parity assertion for the wrong reason (shedding has its own test).
    with multi_worker_plane(engine, prep_path, workers=2, slots_small=16) as (
        port, ring, _, service,
    ):
        with instrument_locks(service, perturb_seed=seed) as san_service, \
                instrument_locks(ring) as san_ring, \
                instrument_locks(engine, perturb_seed=seed) as san_engine:
            results = []
            lock = threading.Lock()

            def call():
                r = predict(port, sample_request)
                with lock:
                    results.append(r)

            threads = [threading.Thread(target=call) for _ in range(12)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
        for sanitizer in (san_service, san_ring, san_engine):
            assert not sanitizer.violations, [
                str(v) for v in sanitizer.violations
            ]
        assert san_service.acquired, "service locks never exercised"
    for status, _, payload in results:
        assert status == 200
        assert payload == json.loads(json.dumps(expected))


# ---------------------------------------------------------- loop hygiene
# Same seed split as the lock-hygiene sweep above: seed 0 in the serial
# tier-1 gate, seeds 1/2 on CI's parallel job.
@pytest.mark.parametrize(
    "seed",
    [0, pytest.param(1, marks=pytest.mark.slow),
     pytest.param(2, marks=pytest.mark.slow)],
)
def test_ring_loop_lag_bounded_under_burst(
    engine, prep_path, sample_request, seed
):
    """Layer 5's runtime half over the real plane: serve.loop_lag_monitor
    arms a LoopLagSanitizer on every forked front end's event loop while
    the engine side runs under seeded schedule perturbation. Through a
    concurrent burst the scraped mlops_tpu_event_loop_lag_ms gauge must
    stay under a bound generous for a CI container yet far below a
    wedged loop (one inline monitor fetch or response encode rides the
    loop for 100ms+), and responses stay bit-identical to the
    single-process path."""
    from mlops_tpu.analysis.lockcheck import instrument_locks

    expected = engine.predict_records(sample_request)
    lag_samples: list = []
    stop = threading.Event()

    with multi_worker_plane(
        engine, prep_path, workers=2, slots_small=16,
        loop_lag_monitor=True, loop_lag_slow_ms=100.0,
    ) as (port, ring, _, service):

        def scrape_lag():
            # Any worker's scrape renders the fleet view from shm; the
            # watchdog overwrites each worker's cell with its last 1 s
            # window max, so sampling faster than the publish cadence
            # observes every window.
            while not stop.is_set():
                with contextlib.suppress(OSError, ValueError):
                    _, _, body = http_exchange(port, "GET", "/metrics")
                    for line in body.decode().splitlines():
                        if line.startswith("mlops_tpu_event_loop_lag_ms{"):
                            lag_samples.append(
                                float(line.rsplit(" ", 1)[1])
                            )
                stop.wait(0.25)

        scraper = threading.Thread(target=scrape_lag)
        scraper.start()
        with instrument_locks(service, perturb_seed=seed), \
                instrument_locks(engine, perturb_seed=seed):
            results: list = []
            lock = threading.Lock()

            def call():
                r = predict(port, sample_request)
                with lock:
                    results.append(r)

            threads = [threading.Thread(target=call) for _ in range(12)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
        # One full watchdog pass after the burst so the busiest window's
        # max is published and scraped before the plane tears down.
        time.sleep(1.5)
        stop.set()
        scraper.join(timeout=10)
    # The always-emit contract: the gauge renders even with zero lag, so
    # an empty sample set means the series vanished, not a smooth loop.
    assert lag_samples, "mlops_tpu_event_loop_lag_ms never rendered"
    assert max(lag_samples) < 500.0, (
        f"event-loop lag {max(lag_samples):.1f}ms on a front-end worker"
    )
    for status, _, payload in results:
        assert status == 200
        assert payload == json.loads(json.dumps(expected))


# ----------------------------------------------------- bench key contract
@pytest.mark.slow
def test_bench_http_multi_stage_key_contract(engine, sample_request):
    """The CI contract for the new bench keys: the http_workers axis
    (http_w{2,4}_req_per_s_c{...}), the http_vs_engine_ratio derived key,
    and shed_503_pct from the overload burst — asserted against the real
    stage function over the session engine."""
    import bench

    base = {"engine_group_req_per_s": 100.0, "http_req_per_s_c8": 1.0}
    out = bench._http_multi_stage(
        engine, engine.bundle, sample_request[0], base
    )
    for workers in (2, 4):
        for c in (1, 8, 32, 128):
            key = f"http_w{workers}_req_per_s_c{c}"
            assert out.get(key, 0) > 0, (key, out)
    assert out["shed_burst_offered"] == 640
    assert 0.0 <= out["shed_503_pct"] <= 100.0
    assert out["shed_burst_errors"] == 0
    assert out["http_vs_engine_ratio"] == pytest.approx(
        out["http_req_per_s_best"] / 100.0, rel=1e-6
    )


# ------------------------------------------------------ config validation
def test_serveconfig_rejects_inconsistent_geometry_with_named_errors():
    cfg = ServeConfig(max_workers=4, max_inflight=4)
    with pytest.raises(ServeConfigError, match="max_inflight"):
        cfg.validate()
    cfg = ServeConfig(workers=2, ring_slots_small=0)
    with pytest.raises(ServeConfigError, match="ring_slots_small"):
        cfg.validate()
    cfg = ServeConfig(workers=2, shed_retry_after_s=0)
    with pytest.raises(ServeConfigError, match="shed_retry_after_s"):
        cfg.validate()
    cfg = ServeConfig(workers=2, engine_respawn_eta_s=0.0)
    with pytest.raises(ServeConfigError, match="engine_respawn_eta_s"):
        cfg.validate()
    cfg = ServeConfig(max_workers=0)
    with pytest.raises(ServeConfigError, match="max_workers"):
        cfg.validate()
    # a valid config chains
    assert ServeConfig(workers=2).validate().workers == 2


def test_engine_stall_answers_504_within_the_deadline_budget(
    engine, prep_path
):
    """Ring-plane deadline contract (ISSUE 9): with the engine stalled (a
    seeded delay fault at serve.engine.dispatch), a request carrying
    x-request-deadline-ms answers the documented 504 within its budget —
    not 503, no Retry-After, no hang — and the plane keeps serving once
    the stall clears (the zombie slot drains via the completion)."""
    from mlops_tpu import faults

    with multi_worker_plane(engine, prep_path, workers=1) as (
        port, ring, procs, service,
    ):
        rec = [{"credit_limit": 9000, "age": 31}]
        status, _, _ = predict(port, rec)
        assert status == 200
        # Arm AFTER the fork: only this (engine-side) process sees the
        # plan, exactly like an engine-process chaos run.
        faults.arm(faults.FaultPlan.from_rules([{
            "point": "serve.engine.dispatch",
            "mode": "delay", "delay_s": 2.0, "max_fires": 1,
        }]))
        try:
            t0 = time.time()
            status, headers, body = http_exchange(
                port, "POST", "/predict", rec,
                headers={"x-request-deadline-ms": "300"},
            )
            elapsed = time.time() - t0
        finally:
            faults.disarm()
        assert status == 504, (status, body)
        assert "retry-after" not in headers  # 504 is not the shed contract
        assert elapsed < 1.5  # the 300 ms budget governed
        # Stall cleared: the same plane serves again (zombie slot drained
        # by the engine's late completion).
        deadline = time.time() + 15
        served = False
        while time.time() < deadline and not served:
            status, _, _ = predict(port, rec)
            served = status == 200
        assert served
        # /metrics exports the robustness counters from any worker.
        status, _, body = http_exchange(port, "GET", "/metrics")
        assert status == 200
        assert b"mlops_tpu_deadline_expired_total" in body
        assert b"mlops_tpu_degraded_dispatch_total" in body
