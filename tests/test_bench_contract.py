"""bench.py's one-JSON-line contract under failure.

The driver parses the LAST stdout line of `python bench.py` as JSON
(`BENCH_r{N}.json`); round 1 lost its benchmark to a crash that printed
a traceback instead. The contract is now: ANY failure still emits one
parseable line with an ``error`` field and a nonzero exit code.
"""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_forced_failure_still_emits_one_json_line():
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        env={
            "PATH": "/usr/bin:/bin:/usr/local/bin",
            "HOME": "/tmp",
            "JAX_PLATFORMS": "bogus-backend",
        },
        capture_output=True,
        text=True,
        timeout=300,
        cwd=REPO,
    )
    assert proc.returncode != 0
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert lines, f"no stdout at all; stderr:\n{proc.stderr[-500:]}"
    payload = json.loads(lines[-1])
    assert payload["metric"] == "inference_p50_latency_ms"
    assert payload["value"] is None
    assert payload["vs_baseline"] == 0.0
    assert "bogus-backend" in payload["error"]


def test_wall_watchdog_emits_json_on_midrun_stall():
    """A mid-run device stall (tunnel hangs AFTER a healthy init) must not
    hang the driver: the wall watchdog prints the error line and
    hard-exits. Simulated with a 1-second budget on the CPU backend."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        env={
            "PATH": "/usr/bin:/bin:/usr/local/bin",
            "HOME": "/tmp",
            "JAX_PLATFORMS": "cpu",
            "BENCH_WALL_TIMEOUT_S": "1",
        },
        capture_output=True,
        text=True,
        timeout=120,
        cwd=REPO,
    )
    assert proc.returncode != 0
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert lines, f"no stdout at all; stderr:\n{proc.stderr[-500:]}"
    payload = json.loads(lines[-1])
    assert payload["value"] is None
    assert "wall timeout" in payload["error"]
