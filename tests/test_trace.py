"""tracewire tests (mlops_tpu/trace/ + the serving-plane threading).

The correctness bar for ISSUE 10:

- inbound ``x-request-id`` echoed on BOTH planes (the caller's trace id
  correlates logs, span record, and response);
- a multi-worker request produces ONE stitched span whose stage stamps
  are monotone and non-overlapping, whose stages sum to its wall clock,
  and which names the compiled entry the ENGINE process chose — the
  engine half-stamps crossing in the shm slot;
- span JSONL survives the SIGTERM drain with zero torn lines (O_APPEND
  single-write discipline);
- the bounded recorder DROPS on overflow (counted in
  ``trace_dropped_total``) instead of ever blocking the hot path;
- /debug/profile start/stop round-trips over the ring to the engine
  process (the only device owner);
- shape histograms render as real Prometheus ``_bucket`` series with
  identical names on both telemetry planes, and the latency histogram
  exports ``_bucket``/``_sum``/``_count`` on both renderers.
"""

import asyncio
import json
import time
from pathlib import Path

import numpy as np
import pytest

from test_frontend import (  # the shared plane harnesses
    http_exchange,
    multi_worker_plane,
    single_process_server,
)

from mlops_tpu.config import TraceConfig, TraceConfigError
from mlops_tpu.trace import (
    ShapeStats,
    Span,
    TraceRecorder,
    load_spans,
    stage_report,
)


@pytest.fixture(scope="module")
def engine(warm_engine):
    return warm_engine  # session-shared warmed engine (conftest)


@pytest.fixture(scope="module")
def prep_path(warm_engine, tmp_path_factory):
    path = tmp_path_factory.mktemp("trace") / "preprocess.npz"
    warm_engine.bundle.preprocessor.save(path)
    return str(path)


# ------------------------------------------------------------------- span
def test_span_stages_are_monotone_and_sum_to_wall():
    span = Span("t1", plane="ring", worker=3)
    span.stamp("admission")
    span.stamp("encode")
    # Cross-process stamp from "the past" (clock skew / reordering must
    # never manufacture a negative stage): clamped to zero duration.
    span.stamp_at("ring_wait", time.monotonic() - 5.0)
    span.stamp("respond")
    record = span.finish(200)
    assert record["stages"]["ring_wait"] == 0.0
    offsets = [offset for _, offset in record["stamps"]]
    assert offsets == sorted(offsets), "stamps must be monotone"
    assert sum(record["stages"].values()) == pytest.approx(
        record["wall_ms"], abs=1e-2
    )


# --------------------------------------------------------------- recorder
def test_recorder_overflow_drops_and_never_blocks(tmp_path):
    drops = []
    recorder = TraceRecorder(
        tmp_path / "spans.jsonl",
        capacity=4,
        flush_interval_s=30.0,  # writer effectively parked: force overflow
        on_drop=lambda n: drops.append(n),
    )
    t0 = time.perf_counter()
    for i in range(100):
        recorder.record({"kind": "span", "trace_id": f"t{i}", "stages": {}})
    enqueue_s = time.perf_counter() - t0
    assert enqueue_s < 1.0, "record() must never block the hot path"
    assert recorder.dropped == 96
    assert len(drops) == 96
    recorder.close()
    lines = (tmp_path / "spans.jsonl").read_text().splitlines()
    assert len(lines) == 4  # capacity survived; every buffered span landed
    for line in lines:
        json.loads(line)


def test_recorder_close_flushes_and_every_line_parses(tmp_path):
    recorder = TraceRecorder(tmp_path / "spans.jsonl", capacity=1024)
    for i in range(64):
        recorder.record(
            {"kind": "span", "trace_id": f"t{i}", "stages": {"respond": 0.1}}
        )
    recorder.close()
    lines = (tmp_path / "spans.jsonl").read_text().splitlines()
    assert len(lines) == 64
    assert all(json.loads(line)["kind"] == "span" for line in lines)


# ----------------------------------------------------------------- shapes
def test_shape_stats_histogram_and_goodput_keys():
    stats = ShapeStats()
    stats.observe("bucket_8", 1, 8)
    stats.observe("bucket_8", 8, 8)
    stats.observe("group_16x1", 4, 16)
    text = "\n".join(stats.render_lines())
    assert 'mlops_tpu_shape_occupancy_bucket{entry="bucket_8",le="0.125"} 1' in text
    assert 'mlops_tpu_shape_occupancy_bucket{entry="bucket_8",le="+Inf"} 2' in text
    assert 'mlops_tpu_shape_occupancy_count{entry="bucket_8"} 2' in text
    assert 'mlops_tpu_requested_rows_total{entry="group_16x1"} 4' in text
    assert 'mlops_tpu_padded_rows_total{entry="group_16x1"} 16' in text
    # waste = 1 - (1+8+4)/(8+8+16) = 1 - 13/32
    assert stats.padding_waste_pct() == pytest.approx(59.375, abs=0.01)
    assert "mlops_tpu_padding_waste_pct 59.375" in text
    assert stats.useful_rows_per_s() >= 0


def test_shape_table_shm_round_trip_renders_same_series():
    from mlops_tpu.trace.shapes import (
        TABLE_KEY_BYTES,
        TABLE_ROWS,
        TABLE_VALS,
        render_table_lines,
    )

    stats = ShapeStats()
    stats.observe("bucket_64", 10, 64)
    stats.observe("group_2x8", 9, 16)
    keys = np.zeros((TABLE_ROWS, TABLE_KEY_BYTES), np.uint8)
    vals = np.zeros((TABLE_ROWS, TABLE_VALS), np.float64)
    stats.write_table(keys, vals)
    direct = [
        line for line in stats.render_lines()
        if "useful_rows_per_s" not in line  # rate base differs by clock read
    ]
    mirrored = [
        line for line in render_table_lines(keys, vals, 10.0)
        if "useful_rows_per_s" not in line
    ]
    assert direct == mirrored


# ------------------------------------------------------- engine span hooks
def test_engine_stamps_span_and_names_the_bucket(engine, sample_request):
    span = Span("eng-1")
    engine.predict_records(sample_request * 3, span=span)
    span.stamp("respond")
    record = span.finish(200)
    assert record["entry"] == "bucket_8"  # 3 rows pad to the 8 bucket
    for stage in ("encode", "dispatch", "device_fetch", "respond"):
        assert stage in record["stages"], record["stages"]
    offsets = [offset for _, offset in record["stamps"]]
    assert offsets == sorted(offsets)


def test_engine_shape_stats_observe_solo_and_grouped(engine, sample_request):
    stats = ShapeStats()
    engine.set_shape_stats(stats)
    try:
        engine.predict_records(sample_request * 3)  # -> bucket_8, 3/8
        engine.predict_group([sample_request, sample_request])  # 2 slots
    finally:
        engine.set_shape_stats(None)
    snap = stats.snapshot()
    assert snap["bucket_8"][1] == 3 and snap["bucket_8"][2] == 8
    group_keys = [k for k in snap if k.startswith("group_")]
    assert group_keys, snap
    slots, rows = group_keys[0].removeprefix("group_").split("x")
    assert snap[group_keys[0]][1] == 2  # two batch-1 requests
    assert snap[group_keys[0]][2] == int(slots) * int(rows)


# ----------------------------------------------------- request-id echo
def test_inbound_request_id_echoed_single_process(engine, sample_request):
    with single_process_server(engine) as port:
        status, headers, _ = http_exchange(
            port, "POST", "/predict", sample_request,
            headers={"x-request-id": "echo-test-42"},
        )
    assert status == 200
    assert headers["x-request-id"] == "echo-test-42"


def test_inbound_request_id_echoed_two_workers(engine, prep_path, sample_request):
    with multi_worker_plane(engine, prep_path, workers=2) as (port, *_):
        status, headers, _ = http_exchange(
            port, "POST", "/predict", sample_request,
            headers={"x-request-id": "echo-ring-7"},
        )
    assert status == 200
    assert headers["x-request-id"] == "echo-ring-7"


# ------------------------------------------------- single-process tracing
def test_single_process_span_records_to_jsonl(engine, sample_request, tmp_path):
    tracer = TraceRecorder(tmp_path / "spans.jsonl", flush_interval_s=0.05)
    with single_process_server(engine, tracer=tracer) as port:
        status, headers, _ = http_exchange(
            port, "POST", "/predict", sample_request,
            headers={"x-request-id": "solo-span-1"},
        )
        assert status == 200
    tracer.close()
    spans = load_spans(tmp_path / "spans.jsonl")
    [span] = [s for s in spans if s["trace_id"] == "solo-span-1"]
    assert span["plane"] == "single"
    assert span["status"] == 200 and span["rows"] == 1
    assert "admission" in span["stages"] and "respond" in span["stages"]
    # The engine half ran in-process: dispatch/fetch stamps present.
    assert "dispatch" in span["stages"] and "device_fetch" in span["stages"]
    assert span.get("entry", "").startswith("bucket_")
    assert sum(span["stages"].values()) == pytest.approx(
        span["wall_ms"], abs=1e-2
    )


# ------------------------------------------------------ ring-plane tracing
def test_ring_plane_stitched_span_and_sigterm_drain(
    engine, prep_path, sample_request, tmp_path
):
    """THE acceptance pin: a multi-worker request returns its trace id
    and produces ONE stitched span — monotone non-overlapping stages
    covering admission -> encode -> ring_wait -> engine_queue ->
    dispatch -> device_fetch -> respond, summing to the span's wall
    clock, naming the engine-chosen compiled entry — and the span JSONL
    survives the SIGTERM drain with zero torn lines."""
    trace = TraceConfig(
        enabled=True, dir=str(tmp_path / "traces"), flush_interval_s=0.05
    )
    walls: dict[str, float] = {}
    with multi_worker_plane(
        engine, prep_path, workers=2, trace=trace
    ) as (port, ring, procs, service):
        assert ring.tracing
        for i in range(4):
            trace_id = f"ring-span-{i}"
            t0 = time.perf_counter()
            status, headers, _ = http_exchange(
                port, "POST", "/predict", sample_request,
                headers={"x-request-id": trace_id},
            )
            walls[trace_id] = (time.perf_counter() - t0) * 1e3
            assert status == 200
            assert headers["x-request-id"] == trace_id
    # Plane drained (SIGTERM via the harness): recorders flushed on exit.
    files = sorted(Path(trace.dir).glob("spans-w*.jsonl"))
    assert files, "no per-worker span files after drain"
    for file in files:
        for line in file.read_text().splitlines():
            json.loads(line)  # zero torn lines
    spans = load_spans(trace.dir)
    by_id = {s["trace_id"]: s for s in spans}
    for i in range(4):
        span = by_id[f"ring-span-{i}"]  # exactly one record per request
        assert span["plane"] == "ring"
        for stage in (
            "admission", "encode", "ring_wait", "engine_queue",
            "dispatch", "device_fetch", "respond",
        ):
            assert stage in span["stages"], (stage, span["stages"])
        offsets = [offset for _, offset in span["stamps"]]
        assert offsets == sorted(offsets), "stitched stamps must be monotone"
        assert sum(span["stages"].values()) == pytest.approx(
            span["wall_ms"], abs=0.05
        )
        # Sanity vs the client-observed wall, with ABSOLUTE slack only: on
        # a contended 1-core box the OS can deschedule the worker between
        # its socket write (client stops its clock) and the respond stamp,
        # so the span wall can legitimately exceed the client wall by
        # scheduler jitter — the bound exists to catch gross pathologies
        # (a stale future stamp stitched in), not scheduling noise.
        assert 0.0 < span["wall_ms"] <= walls[span["trace_id"]] + 100.0
        assert span.get("entry", "").startswith(("bucket_", "group_"))
    assert len([s for s in spans if s["trace_id"].startswith("ring-span")]) == 4


def test_ring_trace_dropped_counter_and_metrics_series(
    engine, prep_path, sample_request
):
    """The dropped-span counter is exported from shm on any worker's
    scrape, zero-baseline (chaos monotonicity discipline)."""
    with multi_worker_plane(engine, prep_path, workers=2) as (port, *_):
        assert http_exchange(port, "POST", "/predict", sample_request)[0] == 200
        status, _, body = http_exchange(port, "GET", "/metrics")
    assert status == 200
    assert b"mlops_tpu_trace_dropped_total 0" in body


# --------------------------------------------------- ring shape telemetry
def test_ring_renders_shape_histograms_from_shm(
    engine, prep_path, sample_request
):
    stats = ShapeStats()
    engine.set_shape_stats(stats)
    try:
        with multi_worker_plane(engine, prep_path, workers=1) as (
            port, ring, _, service,
        ):
            assert http_exchange(
                port, "POST", "/predict", sample_request * 3
            )[0] == 200
            service._write_shapes()  # the telemetry loop's mirror, driven
            status, _, body = http_exchange(port, "GET", "/metrics")
    finally:
        engine.set_shape_stats(None)
    text = body.decode()
    assert status == 200
    assert 'mlops_tpu_shape_occupancy_bucket{entry="bucket_8"' in text
    assert "mlops_tpu_padding_waste_pct" in text
    assert "mlops_tpu_useful_rows_per_s" in text


# -------------------------------------------------- profile over the ring
def test_profile_round_trips_over_the_ring(
    engine, prep_path, sample_request, tmp_path
):
    """/debug/profile start/stop on the 2-worker plane: the front end
    forwards through the ring's control word to the engine process's
    JaxProfiler (the device owner), same statuses as single-process."""
    from mlops_tpu.serve.server import JaxProfiler

    profile_dir = str(tmp_path / "prof")
    with multi_worker_plane(
        engine, prep_path, workers=2, profile_dir=profile_dir
    ) as (port, ring, procs, service):
        service.profiler = JaxProfiler(profile_dir).control
        statuses = []
        for action in ("stop", "start", "start", "stop"):
            status, _, _ = http_exchange(
                port, "POST", f"/debug/profile/{action}"
            )
            statuses.append(status)
        assert statuses == [409, 200, 409, 200]
        assert any(Path(profile_dir).iterdir()), "no trace output captured"


def test_profile_404_when_engine_has_no_profiler(
    engine, prep_path, tmp_path
):
    """profile_dir configured on the front end but no engine-side
    profiler attached (serve.profile_dir empty on the engine): the
    engine answers the control word with 404 rather than wedging the
    front end's poll."""
    with multi_worker_plane(
        engine, prep_path, workers=1, profile_dir=str(tmp_path)
    ) as (port, *_):
        status, _, body = http_exchange(port, "POST", "/debug/profile/start")
    assert status == 404
    assert b"profiling disabled" in body


def test_profile_control_word_unit():
    """The single-word protocol itself: seq/ack pairing, unknown action
    -> 404, handler errors -> 500 (never the collector thread)."""
    from mlops_tpu.serve.ipc import RequestRing, RingService

    class _Stub:
        supports_grouping = False
        monitor_accumulating = False

    ring = RequestRing(workers=1, slots_small=1, slots_large=1, large_rows=8)
    try:
        service = RingService(_Stub(), ring)  # never started: unit-drive
        calls = []

        def profiler(action):
            calls.append(action)
            if action == "stop":
                raise RuntimeError("boom")
            return 200, None

        service.profiler = profiler

        def ack(seq, timeout=10.0):
            # The profiler runs on the service pool (a slow start_trace
            # must never stall the collector); poll the ack word the way
            # a front end does.
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                status = ring.read_profile_ack(seq)
                if status is not None:
                    return status
                time.sleep(0.01)
            raise TimeoutError("no profile ack")

        token = ring.try_claim_profile()
        assert token is not None
        seq = ring.post_profile_request(1)  # start
        service._handle_profile()
        assert ack(seq) == 200
        service._handle_profile()  # same seq: handled once
        assert calls == ["start"]
        seq2 = ring.post_profile_request(2)  # stop -> handler raises
        service._handle_profile()
        assert ack(seq2) == 500
        assert ring.read_profile_ack(seq) is None  # old seq superseded
        seq3 = ring.post_profile_request(9)  # unknown action code
        service._handle_profile()
        assert ack(seq3) == 404
        # Timed-out ack (the front end's 504 path): the CANCEL overwrite
        # must stop a late collector from executing the action the client
        # was told failed, while keeping the seq numbering monotone.
        calls.clear()
        seq4 = ring.post_profile_request(1)  # start...
        # ...504'd before the collector ran:
        ring.cancel_profile_request(seq4, token)
        service._handle_profile()
        assert ack(seq4) == 404  # no-op acknowledged
        assert calls == []  # the start never executed late
        seq5 = ring.post_profile_request(1)
        assert seq5 == seq4 + 1  # numbering survived the cancel
        service._handle_profile()
        assert ack(seq5) == 200 and calls == ["start"]
        ring.release_profile(token)

        # Death tolerance: the claim is a shm LEASE, so a front end
        # killed mid-poll frees by expiry instead of wedging the channel
        # into permanent 409 (every other ring structure survives worker
        # death; this one must too).
        stale = ring.try_claim_profile()
        assert stale is not None
        assert ring.try_claim_profile() is None  # live claim -> busy
        ring.prof_claim[0] = time.monotonic() - 1.0  # claimant died; expired
        live = ring.try_claim_profile()  # lease takeover
        assert live is not None
        # The stalled EX-claimant resumes: its cancel/release must be
        # no-ops against the successor's live lease and pending word.
        seq6 = ring.post_profile_request(1)
        ring.cancel_profile_request(seq6, stale)
        assert int(ring.prof_ctl[0]) & 0xFF == 1  # word not clobbered
        ring.release_profile(stale)
        assert float(ring.prof_claim[0]) == live  # lease still the successor's
        service._handle_profile()
        assert ack(seq6) == 200
        ring.release_profile(live)
        assert float(ring.prof_claim[0]) == 0.0
    finally:
        ring.close()


# ------------------------------------------------------- latency histogram
def test_latency_histogram_bucket_series_on_both_planes(
    engine, prep_path, sample_request
):
    """Satellite pin: the per-plane latency histogram exports real
    Prometheus _bucket/_sum/_count series (le-labelled) on BOTH the
    single-process and ring renderers."""
    with single_process_server(engine) as port:
        assert http_exchange(port, "POST", "/predict", sample_request)[0] == 200
        _, _, body = http_exchange(port, "GET", "/metrics")
    text = body.decode()
    assert (
        'mlops_tpu_request_latency_ms_bucket{le="0.5",tenant="default"}'
        in text
    )
    assert (
        'mlops_tpu_request_latency_ms_bucket{le="+Inf",tenant="default"}'
        in text
    )
    assert "mlops_tpu_request_latency_ms_sum" in text
    assert "mlops_tpu_request_latency_ms_count" in text

    with multi_worker_plane(engine, prep_path, workers=2) as (port, *_):
        assert http_exchange(port, "POST", "/predict", sample_request)[0] == 200
        _, _, body = http_exchange(port, "GET", "/metrics")
    text = body.decode()
    assert (
        'mlops_tpu_request_latency_ms_bucket{le="0.5",worker="0",'
        'tenant="default"}' in text
    )
    assert (
        'mlops_tpu_request_latency_ms_bucket{le="+Inf",worker="1",'
        'tenant="default"}' in text
    )
    assert (
        'mlops_tpu_request_latency_ms_sum{worker="0",tenant="default"}'
        in text
    )
    assert (
        'mlops_tpu_request_latency_ms_count{worker="1",tenant="default"}'
        in text
    )


# ----------------------------------------------------------- trace-report
def test_trace_report_aggregates_p50_p99_per_stage_per_entry(tmp_path):
    recorder = TraceRecorder(tmp_path / "spans.jsonl")
    for i in range(20):
        span = Span(f"r{i}", plane="ring")
        span.entry = "bucket_8" if i % 2 else "group_4x1"
        span.stamp("admission")
        span.stamp("respond")
        recorder.record(span.finish(200))
    recorder.record({"kind": "stage", "stage": "encode"})  # skipped
    recorder.close()
    report = stage_report(load_spans(tmp_path))
    assert report["spans"] == 20
    entries = {g["entry"]: g for g in report["groups"]}
    assert set(entries) == {"bucket_8", "group_4x1"}
    for group in entries.values():
        assert group["requests"] == 10
        assert group["stages"]["admission"]["count"] == 10
        assert group["stages"]["admission"]["p50_ms"] >= 0
        assert group["wall_p99_ms"] >= group["wall_p50_ms"]


def test_trace_report_cli_handler(tmp_path, capsys):
    from mlops_tpu.commands import _trace_report
    from mlops_tpu.config import Config

    recorder = TraceRecorder(tmp_path / "spans.jsonl")
    span = Span("cli-1")
    span.stamp("admission")
    span.stamp("respond")
    recorder.record(span.finish(200))
    recorder.close()
    config = Config()
    config.trace.dir = str(tmp_path)
    assert _trace_report(config) == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    report = json.loads(out)
    assert report["spans"] == 1
    # Empty dir: parseable output, exit 2 (nothing to report).
    config.trace.dir = str(tmp_path / "empty")
    assert _trace_report(config) == 2


# ----------------------------------------------------------------- config
def test_trace_config_validation():
    with pytest.raises(TraceConfigError, match="ring_capacity"):
        TraceConfig(ring_capacity=0).validate()
    with pytest.raises(TraceConfigError, match="flush_interval_s"):
        TraceConfig(flush_interval_s=0).validate()
    with pytest.raises(TraceConfigError, match="trace.dir"):
        TraceConfig(enabled=True, dir="").validate()
    assert TraceConfig(enabled=True).validate().enabled


# --------------------------------------------------------- StageClock sink
def test_stage_clock_emits_span_events_to_sink(tmp_path):
    from mlops_tpu.utils.timing import StageClock

    recorder = TraceRecorder(tmp_path / "spans.jsonl")
    clock = StageClock(sink=recorder.stage_sink("bulk"))
    with clock.stage("encode", items=3):
        pass
    with clock.stage("compute"):
        pass
    recorder.close()
    records = [
        json.loads(line)
        for line in (tmp_path / "spans.jsonl").read_text().splitlines()
    ]
    assert [r["stage"] for r in records] == ["encode", "compute"]
    assert all(r["kind"] == "stage" and r["source"] == "bulk" for r in records)
    assert records[0]["items"] == 3
    # report() still works with a sink attached (the existing contract).
    assert set(clock.report(1.0)) == {"encode", "compute"}


def test_stream_scoring_emits_stage_records(tiny_pipeline, tmp_path):
    """The production wiring: `score-batch score.streaming=true` with
    tracing armed streams every pipeline stage execution into the span
    JSONL (the bulk path's half of the queryable-log story)."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.data import generate_synthetic, write_csv_columns
    from mlops_tpu.data.stream import score_csv_stream

    _, result = tiny_pipeline
    bundle = load_bundle(result.bundle_dir)
    columns, labels = generate_synthetic(400, seed=3)
    write_csv_columns(tmp_path / "in.csv", columns, labels)
    recorder = TraceRecorder(tmp_path / "spans-bulk.jsonl")
    stats = score_csv_stream(
        bundle,
        tmp_path / "in.csv",
        tmp_path / "out.csv",
        chunk_rows=256,
        pipeline_depth=1,
        stage_sink=recorder.stage_sink("score-stream"),
    )
    recorder.close()
    assert stats["rows"] == 400
    records = [
        json.loads(line)
        for line in (tmp_path / "spans-bulk.jsonl").read_text().splitlines()
    ]
    assert records, "no stage records landed"
    assert all(
        r["kind"] == "stage" and r["source"] == "score-stream"
        and r["dur_ms"] >= 0 for r in records
    )
    assert {"encode", "compute"} <= {r["stage"] for r in records}
