"""BERT tabular-as-text family: layout, tokenization, training, sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlops_tpu.config import ModelConfig, TrainConfig
from mlops_tpu.models import build_model, init_params
from mlops_tpu.models.bert import (
    CLS_ID,
    SEP_ID,
    TokenLayout,
    tokenize,
)
from mlops_tpu.schema import SCHEMA

SMALL = ModelConfig(family="bert", token_dim=32, depth=2, heads=4, dropout=0.0)


def _layout() -> TokenLayout:
    return TokenLayout(SCHEMA.cards, SCHEMA.num_numeric, num_bins=8)


def test_layout_blocks_are_disjoint_and_cover_vocab():
    layout = _layout()
    spans = [(0, 4)]  # specials
    spans.append((layout.name_offset, layout.name_offset + layout.num_features))
    for off, card in zip(layout.cat_offsets, layout.cards):
        spans.append((off, off + card))
    for off in layout.bin_offsets:
        spans.append((off, off + layout.num_bins))
    spans.sort()
    for (_, end), (start, _) in zip(spans, spans[1:]):
        assert end == start, "token blocks must tile the id space exactly"
    assert spans[-1][1] == layout.vocab_size
    assert layout.seq_len == 2 + 2 * SCHEMA.num_features


def test_tokenize_shapes_and_ranges():
    layout = _layout()
    rng = np.random.default_rng(0)
    n = 16
    cat = jnp.asarray(
        rng.integers(0, min(SCHEMA.cards), (n, SCHEMA.num_categorical)),
        jnp.int32,
    )
    num = jnp.asarray(rng.normal(size=(n, SCHEMA.num_numeric)), jnp.float32)
    toks = tokenize(cat, num, layout)
    assert toks.shape == (n, layout.seq_len)
    toks = np.asarray(toks)
    assert (toks[:, 0] == CLS_ID).all()
    assert (toks[:, -1] == SEP_ID).all()
    assert toks.min() >= 0 and toks.max() < layout.vocab_size
    # Extreme numerics clamp into the first/last bin, never out of block.
    extreme = jnp.asarray(
        np.full((2, SCHEMA.num_numeric), 1e6, np.float32)
    )
    toks2 = np.asarray(tokenize(cat[:2], extreme, layout))
    assert toks2.max() < layout.vocab_size


def test_bert_forward_shape_and_determinism():
    model = build_model(SMALL)
    variables = init_params(model, jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    cat = jnp.asarray(
        rng.integers(0, 2, (8, SCHEMA.num_categorical)), jnp.int32
    )
    num = jnp.asarray(rng.normal(size=(8, SCHEMA.num_numeric)), jnp.float32)
    logits = model.apply(variables, cat, num, train=False)
    assert logits.shape == (8,)
    assert logits.dtype == jnp.float32
    again = model.apply(variables, cat, num, train=False)
    np.testing.assert_array_equal(np.asarray(logits), np.asarray(again))


# Heaviest end-to-end path (~60s serial on CPU): excluded from the
# timed tier-1 gate; CI's parallel pytest job still runs it.
@pytest.mark.slow
def test_bert_trains_end_to_end(tmp_path):
    """Full pipeline (train -> bundle -> reload) with the bert family."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.config import Config
    from mlops_tpu.train.pipeline import run_training

    config = Config()
    config.data.rows = 1500
    config.model = SMALL
    config.train = TrainConfig(steps=30, eval_every=30, batch_size=128)
    config.registry.root = str(tmp_path / "registry")
    config.registry.run_root = str(tmp_path / "runs")
    result = run_training(config)
    assert result.train_result.metrics["validation_roc_auc_score"] > 0.4
    bundle = load_bundle(result.bundle_dir)
    assert bundle.model_config.family == "bert"


def test_bert_sharded_train_step_dp_tp():
    """One DP x TP step over the fake 8-device mesh (config 5 shape)."""
    from mlops_tpu.parallel import make_mesh, make_sharded_train_step
    from mlops_tpu.train.loop import TrainState, make_optimizer

    mesh = make_mesh(8, model_parallel=2)
    model = build_model(SMALL)
    variables = init_params(model, jax.random.PRNGKey(0))
    tconfig = TrainConfig(batch_size=16, steps=1)
    optimizer = make_optimizer(tconfig)
    step_fn, _ = make_sharded_train_step(
        model, optimizer, tconfig, mesh, variables["params"]
    )
    state = TrainState(
        params=variables["params"],
        opt_state=optimizer.init(variables["params"]),
        step=jnp.asarray(0, jnp.int32),
        rng=jax.random.PRNGKey(1),
    )
    rng = np.random.default_rng(0)
    cat = jnp.asarray(
        rng.integers(0, 2, (16, SCHEMA.num_categorical)), jnp.int32
    )
    num = jnp.asarray(rng.normal(size=(16, SCHEMA.num_numeric)), jnp.float32)
    lab = jnp.asarray((rng.random(16) < 0.2).astype(np.float32))
    new_state, loss = step_fn(state, cat, num, lab, jax.random.PRNGKey(2))
    assert np.isfinite(float(loss))
    assert int(new_state.step) == 1
