"""Masked-feature pretraining: objective learns, trunk transfers."""

import jax
import numpy as np
import pytest

from mlops_tpu.config import ModelConfig
from mlops_tpu.models import build_model, init_params
from mlops_tpu.train.pretrain import (
    build_mlm,
    fine_tune_params,
    pretrain_bert,
)

SMALL = ModelConfig(family="bert", token_dim=32, depth=2, heads=4, dropout=0.0)


def test_mlm_loss_decreases(encoded_small):
    _, ds = encoded_small
    result = pretrain_bert(SMALL, ds, steps=120, batch_size=128, seed=0)
    assert result.losses[-1] < result.losses[0] * 0.8, result.losses
    assert np.isfinite(result.losses[-1])


def test_value_positions_are_value_tokens():
    model = build_mlm(SMALL)
    layout = model.layout
    pos = model.value_positions()
    assert len(pos) == layout.num_features
    assert pos[0] == 2 and pos[-1] == layout.seq_len - 2


def test_trunk_transfer_into_classifier(encoded_small):
    _, ds = encoded_small
    pre = pretrain_bert(SMALL, ds, steps=20, batch_size=64, seed=1)

    classifier = build_model(SMALL)
    fresh = init_params(classifier, jax.random.PRNGKey(0))
    grafted = fine_tune_params(pre, fresh)

    # Trunk params must be the pretrained ones, heads the fresh ones.
    np.testing.assert_array_equal(
        np.asarray(grafted["params"]["tok_embed"]["embedding"]),
        np.asarray(pre.params["tok_embed"]["embedding"]),
    )
    assert "mlm_head" not in grafted["params"]
    assert "pooler" in grafted["params"]

    # And the classifier must run with the grafted tree.
    rng = np.random.default_rng(0)
    cat = np.asarray(ds.cat_ids[:4])
    num = np.asarray(ds.numeric[:4])
    logits = classifier.apply(grafted, cat, num, train=False)
    assert logits.shape == (4,)
    assert np.isfinite(np.asarray(logits)).all()


# Heaviest end-to-end path (~60s serial on CPU): excluded from the
# timed tier-1 gate; CI's parallel pytest job still runs it.
@pytest.mark.slow
def test_pretrain_cli_to_finetune_roundtrip(tmp_path):
    """pretrain CLI output feeds train train.init_params end-to-end."""
    from mlops_tpu.config import Config, TrainConfig
    from mlops_tpu.train.pipeline import run_training
    from mlops_tpu.train.pretrain import pretrain_bert, save_pretrained
    from mlops_tpu.data import generate_synthetic, Preprocessor

    columns, _ = generate_synthetic(800, seed=5)
    prep = Preprocessor.fit(columns)
    ds = prep.encode(columns)
    pre = pretrain_bert(SMALL, ds, steps=15, batch_size=64, seed=2)
    path = tmp_path / "pretrained.msgpack"
    save_pretrained(pre, path)

    config = Config()
    config.data.rows = 800
    config.model = SMALL
    config.train = TrainConfig(
        steps=15, eval_every=15, batch_size=64, init_params=str(path)
    )
    config.registry.root = str(tmp_path / "registry")
    config.registry.run_root = str(tmp_path / "runs")
    result = run_training(config, register=False)
    assert np.isfinite(
        result.train_result.metrics["validation_roc_auc_score"]
    )
