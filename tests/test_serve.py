"""Serving tests: engine semantics + real-socket HTTP round trips.

Upgrades the reference's 200-only smoke test (SURVEY.md SS4: CI curls
`app/sample-request.json` and checks the status code, response body never
validated) into payload-asserting golden tests.
"""

import asyncio
import json
import time

import numpy as np
import pytest

from mlops_tpu.bundle import load_bundle
from mlops_tpu.config import ServeConfig
from mlops_tpu.schema import FEATURE_NAMES
from mlops_tpu.serve import HttpServer, InferenceEngine


@pytest.fixture(scope="module")
def engine(warm_engine):
    return warm_engine  # session-shared warmed engine (conftest)


# ------------------------------------------------------------------ engine


def test_engine_padding_invariance(engine, sample_request):
    """Bucket padding must not change any statistic: a 3-row request (padded
    to 8) and the same rows at exact shape agree."""
    records = sample_request * 3
    padded = engine.predict_records(records)
    # Bypass bucketing: exact-shape path.
    from mlops_tpu.schema import records_to_columns

    ds = engine.bundle.preprocessor.encode(records_to_columns(records))
    big = InferenceEngine(engine.bundle, buckets=(3,))
    exact = big.predict_arrays(ds.cat_ids, ds.numeric)
    np.testing.assert_allclose(
        padded["predictions"], exact["predictions"], rtol=1e-6
    )
    np.testing.assert_array_equal(padded["outliers"], exact["outliers"])
    for name in FEATURE_NAMES:
        assert abs(
            padded["feature_drift_batch"][name]
            - exact["feature_drift_batch"][name]
        ) < 1e-5


def test_engine_oversized_batch(engine, sample_request):
    out = engine.predict_records(sample_request * 100)  # > max bucket 64
    assert len(out["predictions"]) == 100
    assert len(out["outliers"]) == 100


def test_engine_response_contract(engine, sample_request):
    out = engine.predict_records(sample_request)
    assert set(out) == {"predictions", "outliers", "feature_drift_batch"}
    assert len(out["predictions"]) == 1
    assert 0.0 <= out["predictions"][0] <= 1.0
    assert out["outliers"][0] in (0.0, 1.0)
    assert list(out["feature_drift_batch"]) == list(FEATURE_NAMES)


# ------------------------------------------------------------- HTTP server


async def _http(server_port_payloads):
    """Open the server on an ephemeral port, run client exchanges, return
    (status, headers, body-json) per exchange."""
    server, exchanges = server_port_payloads
    srv = await server.start()
    port = srv.sockets[0].getsockname()[1]
    results = []
    try:
        for method, path, body in exchanges:
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            data = b"" if body is None else json.dumps(body).encode()
            request = (
                f"{method} {path} HTTP/1.1\r\nhost: t\r\n"
                f"content-length: {len(data)}\r\nconnection: close\r\n\r\n"
            ).encode() + data
            writer.write(request)
            await writer.drain()
            raw = await reader.read()
            writer.close()
            head, _, payload = raw.partition(b"\r\n\r\n")
            status = int(head.split(b" ")[1])
            results.append((status, head.decode("latin1"), payload))
    finally:
        srv.close()
        await srv.wait_closed()
    return results


def _run_exchanges(engine, exchanges, port=0):
    config = ServeConfig(host="127.0.0.1", port=port)
    server = HttpServer(engine, config)
    return asyncio.run(_http((server, exchanges)))


def test_http_predict_golden(engine, sample_request):
    """The reference's exact smoke payload over a real socket -> validated
    body (vs the reference CI's unchecked `cat`, `deploy-kubernetes.yml:271`).
    """
    [(status, _, body)] = _run_exchanges(
        engine, [("POST", "/predict", sample_request)]
    )
    assert status == 200
    payload = json.loads(body)
    assert set(payload) == {"predictions", "outliers", "feature_drift_batch"}
    assert len(payload["predictions"]) == 1
    assert 0.0 <= payload["predictions"][0] <= 1.0
    # Determinism: same request -> identical response.
    [(_, _, body2)] = _run_exchanges(
        engine, [("POST", "/predict", sample_request)]
    )
    assert json.loads(body2)["predictions"] == payload["predictions"]


def test_http_validation_and_probes(engine):
    results = _run_exchanges(
        engine,
        [
            ("POST", "/predict", [{"age": "not-a-number"}]),
            ("GET", "/healthz/live", None),
            ("GET", "/healthz/ready", None),
            ("GET", "/metrics", None),
            ("GET", "/nope", None),
            ("GET", "/", None),
        ],
    )
    statuses = [r[0] for r in results]
    assert statuses == [422, 200, 200, 200, 404, 200]
    assert b"mlops_tpu_requests_total" in results[3][2]
    assert b"credit-default-api" in results[5][2]


def test_http_defaults_fill_missing_fields(engine):
    # Reference parity: every LoanApplicant field has a default
    # (`app/model.py:12-34`), so an empty record is valid.
    [(status, _, body)] = _run_exchanges(engine, [("POST", "/predict", [{}])])
    assert status == 200
    assert len(json.loads(body)["predictions"]) == 1


def test_http_malformed_json_rejected(engine):
    config = ServeConfig(host="127.0.0.1", port=0)
    server = HttpServer(engine, config)

    async def go():
        srv = await server.start()
        port = srv.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        body = b"{not json"
        writer.write(
            (
                f"POST /predict HTTP/1.1\r\nhost: t\r\n"
                f"content-length: {len(body)}\r\nconnection: close\r\n\r\n"
            ).encode()
            + body
        )
        await writer.drain()
        raw = await reader.read()
        writer.close()
        srv.close()
        await srv.wait_closed()
        return int(raw.split(b" ")[1])

    assert asyncio.run(go()) == 422


def test_http_empty_request_no_drift_poison(engine):
    # An empty list is valid, returns empty outputs, and must not report
    # drift (an all-padded batch has no signal).
    [(status, _, body)] = _run_exchanges(engine, [("POST", "/predict", [])])
    assert status == 200
    payload = json.loads(body)
    assert payload["predictions"] == []
    assert all(v == 0.0 for v in payload["feature_drift_batch"].values())


def test_metrics_unknown_route_bounded(engine):
    results = _run_exchanges(
        engine,
        [("GET", f"/scan-{i}", None) for i in range(5)] + [("GET", "/metrics", None)],
    )
    body = results[-1][2].decode()
    assert 'route="<other>"' in body
    assert "/scan-0" not in body


def test_http_max_batch_cap(engine, sample_request):
    config = ServeConfig(host="127.0.0.1", port=0, max_batch=4)
    server = HttpServer(engine, config)
    [(status, _, body)] = asyncio.run(
        _http((server, [("POST", "/predict", sample_request * 5)]))
    )
    assert status == 413
    assert b"max_batch" in body


def test_http_bad_content_length(engine):
    config = ServeConfig(host="127.0.0.1", port=0)
    server = HttpServer(engine, config)

    async def go():
        srv = await server.start()
        port = srv.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(
            b"POST /predict HTTP/1.1\r\nhost: t\r\ncontent-length: abc\r\n\r\n"
        )
        await writer.drain()
        raw = await reader.read()
        writer.close()
        srv.close()
        await srv.wait_closed()
        return int(raw.split(b" ")[1])

    assert asyncio.run(go()) == 400


def test_readiness_gate(tiny_pipeline):
    _, result = tiny_pipeline
    bundle = load_bundle(result.bundle_dir)
    cold = InferenceEngine(bundle, buckets=(1,))  # no warmup
    [(status, _, body)] = _run_exchanges(cold, [("GET", "/healthz/ready", None)])
    assert status == 503


def test_profile_endpoints(engine, tmp_path):
    """jax.profiler trace start/stop over the socket (SURVEY.md SS5.1)."""
    config = ServeConfig(host="127.0.0.1", port=0, profile_dir=str(tmp_path))
    server = HttpServer(engine, config)
    exchanges = [
        ("POST", "/debug/profile/stop", None),   # nothing running -> 409
        ("POST", "/debug/profile/start", None),  # -> 200 tracing
        ("POST", "/debug/profile/start", None),  # already running -> 409
        ("POST", "/debug/profile/stop", None),   # -> 200 stopped
    ]
    results = asyncio.run(_http((server, exchanges)))
    assert [s for s, _, _ in results] == [409, 200, 409, 200]
    assert any(tmp_path.iterdir()), "trace output expected in profile_dir"


def test_profile_disabled(engine):
    config = ServeConfig(host="127.0.0.1", port=0, profile_dir="")
    server = HttpServer(engine, config)
    [(status, _, _)] = asyncio.run(
        _http((server, [("POST", "/debug/profile/start", None)]))
    )
    assert status == 404


def test_openapi_document(engine):
    """GET /openapi.json serves a valid document generated from the SAME
    pydantic models that validate requests (reference parity: FastAPI's
    auto-docs at `/`, `app/main.py:37`), and `/` serves the Swagger page."""
    [(status, _, body), (hstatus, hhead, hbody)] = _run_exchanges(
        engine, [("GET", "/openapi.json", None), ("GET", "/", None)]
    )
    assert status == 200
    doc = json.loads(body)
    assert doc["openapi"].startswith("3.")
    assert "/predict" in doc["paths"]
    applicant = doc["components"]["schemas"]["LoanApplicant"]
    assert len(applicant["properties"]) == 23
    request_schema = doc["paths"]["/predict"]["post"]["requestBody"]
    assert request_schema["required"] is True
    output = doc["components"]["schemas"]["FeatureBatchDrift"]
    assert len(output["properties"]) == 23
    assert hstatus == 200 and b"swagger-ui" in hbody


def test_sigterm_graceful_drain():
    """SIGTERM flips readiness, closes IDLE keep-alive connections
    immediately, lets an IN-FLIGHT request finish its response, and
    _serve returns promptly (K8s rollout contract) — the idle-connection
    case is what stalls a naive wait_closed() shutdown forever."""
    import os
    import signal
    import time as _time

    from mlops_tpu.serve.server import _serve

    class StubEngine:
        ready = False
        max_bucket = 64
        supports_grouping = False

        def warmup(self):
            self.ready = True

        def predict_records(self, records):
            _time.sleep(0.8)  # in-flight work straddling the SIGTERM
            return {
                "predictions": [0.5],
                "outliers": [0.0],
                "feature_drift_batch": dict.fromkeys(FEATURE_NAMES, 0.0),
            }

    engine = StubEngine()
    body = json.dumps([{}]).encode()
    request = (
        b"POST /predict HTTP/1.1\r\nhost: t\r\n"
        b"content-type: application/json\r\n"
        + f"content-length: {len(body)}\r\n\r\n".encode()
        + body
    )

    async def run():
        config = ServeConfig(host="127.0.0.1", port=5173)
        serve_task = asyncio.create_task(_serve(engine, config))
        for _ in range(100):  # wait for bind + warmup
            if engine.ready:
                break
            await asyncio.sleep(0.05)
        assert engine.ready

        # Idle keep-alive connection: must be closed by the drain, not
        # hold shutdown open.
        idle_reader, idle_writer = await asyncio.open_connection(
            "127.0.0.1", config.port
        )
        # In-flight request: send, then SIGTERM while the stub predict
        # sleeps; the response must still arrive complete.
        busy_reader, busy_writer = await asyncio.open_connection(
            "127.0.0.1", config.port
        )
        busy_writer.write(request)
        await busy_writer.drain()
        await asyncio.sleep(0.2)  # let the exchange enter _route

        t0 = asyncio.get_running_loop().time()
        os.kill(os.getpid(), signal.SIGTERM)

        head = await asyncio.wait_for(busy_reader.readline(), timeout=10)
        assert b"200" in head
        raw = await asyncio.wait_for(busy_reader.read(), timeout=10)
        assert b"predictions" in raw
        assert b"connection: close" in (head + raw).lower()

        # The idle connection gets EOF instead of stalling shutdown.
        assert await asyncio.wait_for(idle_reader.read(), timeout=10) == b""

        await asyncio.wait_for(serve_task, timeout=10)
        elapsed = asyncio.get_running_loop().time() - t0
        assert elapsed < 8, f"drain took {elapsed:.1f}s"
        for w in (idle_writer, busy_writer):
            w.close()

    asyncio.run(run())
    assert engine.ready is False  # readiness stays down through exit


def test_inbound_request_id_is_honored_and_echoed(engine, sample_request):
    """A well-formed x-request-id correlates the caller's trace end to end:
    echoed as a response header and stamped on both log events; malformed
    ids are replaced with a fresh hex (log-injection gate)."""
    config = ServeConfig(host="127.0.0.1", port=0)
    server = HttpServer(engine, config)

    async def run():
        srv = await server.start()
        port = srv.sockets[0].getsockname()[1]
        out = []
        try:
            for rid in ("trace-abc_123", "bad id with spaces", "x" * 100):
                reader, writer = await asyncio.open_connection("127.0.0.1", port)
                data = json.dumps(sample_request).encode()
                writer.write(
                    (
                        f"POST /predict HTTP/1.1\r\nhost: t\r\n"
                        f"x-request-id: {rid}\r\n"
                        f"content-length: {len(data)}\r\nconnection: close\r\n\r\n"
                    ).encode()
                    + data
                )
                await writer.drain()
                raw = await reader.read()
                writer.close()
                head = raw.partition(b"\r\n\r\n")[0].decode("latin1")
                echoed = [
                    line.split(":", 1)[1].strip()
                    for line in head.splitlines()
                    if line.lower().startswith("x-request-id:")
                ]
                out.append((rid, echoed[0]))
        finally:
            srv.close()
            await srv.wait_closed()
        return out

    results = asyncio.run(run())
    assert results[0] == ("trace-abc_123", "trace-abc_123")  # honored
    for sent, echoed in results[1:]:
        assert echoed != sent  # malformed -> replaced
        assert len(echoed) == 32 and all(c in "0123456789abcdef" for c in echoed)


def test_request_deadline_504s_on_stalled_device(engine, sample_request):
    """A wedged predict path (stalled device) must answer the documented
    504 within the deadline instead of hanging every in-flight connection
    (observed live: a tunnel-attached chip stalling dispatches for 40+
    minutes). 504, not 503: deadline is distinct from the shed path,
    which alone carries Retry-After (ISSUE 9)."""
    config = ServeConfig(host="127.0.0.1", port=0, request_timeout_s=0.3)
    server = HttpServer(engine, config)

    async def hang_forever(records, deadline=None):
        await asyncio.sleep(3600)

    server.batcher.predict = hang_forever  # simulate the stall

    async def run():
        srv = await server.start()
        port = srv.sockets[0].getsockname()[1]
        try:
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            data = json.dumps(sample_request).encode()
            writer.write(
                (
                    f"POST /predict HTTP/1.1\r\nhost: t\r\n"
                    f"content-length: {len(data)}\r\nconnection: close\r\n\r\n"
                ).encode()
                + data
            )
            await writer.drain()
            raw = await asyncio.wait_for(reader.read(), timeout=5)
        finally:
            srv.close()
            await srv.wait_closed()
        head, _, body = raw.partition(b"\r\n\r\n")
        return int(head.split(b" ")[1]), json.loads(body)

    status, payload = asyncio.run(run())
    assert status == 504
    assert "deadline" in payload["detail"]


def test_deadline_header_sheds_dead_work_before_the_engine(
    engine, sample_request
):
    """An already-expired x-request-deadline-ms budget answers the
    documented 504 WITHOUT the engine (or batcher) ever being touched —
    the dead-work shed — and the shed is counted in
    mlops_tpu_deadline_expired_total (ISSUE 9)."""
    config = ServeConfig(host="127.0.0.1", port=0)
    server = HttpServer(engine, config)
    touched = []

    async def must_not_run(records, deadline=None):
        touched.append(records)
        return {}

    server.batcher.predict = must_not_run

    async def run():
        srv = await server.start()
        port = srv.sockets[0].getsockname()[1]
        try:
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            data = json.dumps(sample_request).encode()
            writer.write(
                (
                    f"POST /predict HTTP/1.1\r\nhost: t\r\n"
                    f"content-length: {len(data)}\r\n"
                    # 1 ms budget, then stall the body so it is spent
                    # before the request completes admission.
                    f"x-request-deadline-ms: 1\r\nconnection: close\r\n\r\n"
                ).encode()
            )
            await writer.drain()
            await asyncio.sleep(0.05)  # budget expires while body pends
            writer.write(data)
            await writer.drain()
            raw = await asyncio.wait_for(reader.read(), timeout=5)
        finally:
            srv.close()
            await srv.wait_closed()
        head, _, body = raw.partition(b"\r\n\r\n")
        return int(head.split(b" ")[1]), json.loads(body)

    status, payload = asyncio.run(run())
    assert status == 504
    assert "deadline" in payload["detail"]
    assert touched == []  # the engine path never ran — dead work shed
    assert server.metrics.deadline_expired == 1
    assert "mlops_tpu_deadline_expired_total 1" in server.metrics.render()


def test_deadline_header_tightens_the_server_timeout(engine, sample_request):
    """A live (not yet expired) budget bounds the wait on a stalled
    engine: the 504 lands within the header budget even though
    serve.request_timeout_s is far larger."""
    config = ServeConfig(host="127.0.0.1", port=0, request_timeout_s=30.0)
    server = HttpServer(engine, config)

    async def hang_forever(records, deadline=None):
        await asyncio.sleep(3600)

    server.batcher.predict = hang_forever

    async def run():
        srv = await server.start()
        port = srv.sockets[0].getsockname()[1]
        try:
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            data = json.dumps(sample_request).encode()
            writer.write(
                (
                    f"POST /predict HTTP/1.1\r\nhost: t\r\n"
                    f"content-length: {len(data)}\r\n"
                    f"x-request-deadline-ms: 200\r\nconnection: close\r\n\r\n"
                ).encode()
                + data
            )
            await writer.drain()
            t0 = time.perf_counter()
            raw = await asyncio.wait_for(reader.read(), timeout=5)
            elapsed = time.perf_counter() - t0
        finally:
            srv.close()
            await srv.wait_closed()
        head, _, body = raw.partition(b"\r\n\r\n")
        return int(head.split(b" ")[1]), elapsed

    status, elapsed = asyncio.run(run())
    assert status == 504
    assert elapsed < 2.0  # the 200 ms budget governed, not the 30 s knob


def test_batcher_purges_expired_entries_engine_side(engine, sample_request):
    """The micro-batcher's claim-time purge completes an expired entry
    with DeadlineExceeded INSTEAD of dispatching it (dead-work shedding):
    the handler answers 504 and the engine never sees the request."""
    import concurrent.futures

    from mlops_tpu.serve.batcher import MicroBatcher
    from mlops_tpu.serve.wire import DeadlineExceeded

    dispatched = []

    class Recorder:
        supports_grouping = True

        def predict_records(self, records):
            dispatched.append(records)
            return {"predictions": [0.0]}

        def predict_group(self, requests):
            dispatched.extend(requests)
            return [{"predictions": [0.0]} for _ in requests]

    async def run():
        loop = asyncio.get_running_loop()
        pool = concurrent.futures.ThreadPoolExecutor(2)
        batcher = MicroBatcher(Recorder(), pool, window_ms=20.0, max_group=8)
        # Seed the queue so the entry below is NOT idle-fast-pathed.
        warm = asyncio.ensure_future(batcher.predict(sample_request))
        await asyncio.sleep(0)
        expired = asyncio.ensure_future(
            batcher.predict(sample_request, deadline=loop.time() - 0.001)
        )
        results = await asyncio.gather(warm, expired, return_exceptions=True)
        pool.shutdown(wait=True)
        return results

    warm_result, expired_result = asyncio.run(run())
    assert isinstance(warm_result, dict)  # the live entry still served
    assert isinstance(expired_result, DeadlineExceeded)
    # Exactly one request reached the engine: the expired one was purged.
    assert len(dispatched) == 1


def test_degraded_dispatch_falls_back_to_next_warmed_bucket(
    engine, sample_request
):
    """A compile/cache failure for an unwarmed bucket (injected at
    serve.engine.compile) degrades to the next-larger WARMED bucket with
    a bit-identical response and a degraded_dispatch_total increment —
    never a 500 (ISSUE 9 degraded-mode contract)."""
    from mlops_tpu import faults

    record = sample_request[0]
    records = [dict(record) for _ in range(3)]
    baseline = engine.predict_records(records)
    before = engine.degraded_dispatch_total
    # Make bucket 8 (the 3-row target) unwarmed, and fail its compile.
    with engine._compile_lock:
        saved = engine._exec.pop(("bucket", 8))
    try:
        faults.arm(
            faults.FaultPlan.from_rules(
                [{"point": "serve.engine.compile", "mode": "raise"}]
            )
        )
        degraded = engine.predict_records(records)
    finally:
        faults.disarm()
        with engine._compile_lock:
            engine._exec[("bucket", 8)] = saved
    assert degraded == baseline  # masked padding = identical statistics
    assert engine.degraded_dispatch_total == before + 1
    # With the fault disarmed and the entry restored, the target bucket
    # serves again without touching the degraded path.
    assert engine.predict_records(records) == baseline
    assert engine.degraded_dispatch_total == before + 1
