"""gs:// storage layer + GCS-rooted registry against an in-memory fake.

The fake implements the slice of the GCS JSON API the client speaks
(media download/upload, metadata GET, prefix list with pagination), so
the whole gs:// path — ingest, registry register/resolve/promote — runs
in unit tests with zero network. Analogue under test: the reference's
DBFS dataset staging + MLflow registry reachability
(`deploy-infrastructure.yml:195-198`, `02-register-model.ipynb:461-470`).
"""

import json
import urllib.parse

import numpy as np
import pytest

from mlops_tpu.utils import storage


class FakeGCS:
    """In-memory bucket behind the GCSClient transport contract."""

    def __init__(self):
        self.objects: dict[str, bytes] = {}  # "bucket/key" -> bytes
        self.generations: dict[str, int] = {}
        self.calls: list[str] = []

    def transport(self, method, url, data, headers):
        self.calls.append(f"{method} {url}")
        parsed = urllib.parse.urlparse(url)
        query = urllib.parse.parse_qs(parsed.query)
        path = urllib.parse.unquote(parsed.path)
        if parsed.hostname == "metadata.google.internal":
            return 200, json.dumps({"access_token": "fake-token"}).encode()
        assert headers.get("Authorization"), "unauthenticated GCS call"
        if path.startswith("/upload/storage/v1/b/"):
            bucket = path.split("/")[5]
            key = query["name"][0]
            full = f"{bucket}/{key}"
            self.objects[full] = data
            self.generations[full] = self.generations.get(full, 0) + 1
            return 200, b"{}"
        if path.startswith("/storage/v1/b/"):
            parts = path.split("/", 6)  # ['', 'storage', 'v1', 'b', bkt, 'o', key?]
            bucket = parts[4]
            key = parts[6] if len(parts) > 6 else None
            if key is None:  # list
                prefix = query.get("prefix", [""])[0]
                delimiter = query.get("delimiter", [None])[0]
                names = sorted(
                    k[len(bucket) + 1 :]
                    for k in self.objects
                    if k.startswith(f"{bucket}/{prefix}")
                )
                if delimiter:
                    # Collapse keys past the delimiter into "directory"
                    # prefixes, per the GCS JSON API contract.
                    prefixes, leaves = set(), []
                    for n in names:
                        rest = n[len(prefix) :]
                        if delimiter in rest:
                            prefixes.add(
                                prefix + rest.split(delimiter, 1)[0] + delimiter
                            )
                        else:
                            leaves.append(n)
                    payload = {
                        "items": [{"name": n} for n in leaves],
                        "prefixes": sorted(prefixes),
                    }
                    return 200, json.dumps(payload).encode()
                page = int(query.get("pageToken", ["0"])[0] or 0)
                chunk, nxt = names[page : page + 2], page + 2
                payload = {"items": [{"name": n} for n in chunk]}
                if nxt < len(names):
                    payload["nextPageToken"] = str(nxt)
                return 200, json.dumps(payload).encode()
            blob = self.objects.get(f"{bucket}/{key}")
            if blob is None:
                return 404, b"{}"
            if query.get("alt") == ["media"]:
                return 200, blob
            meta = {
                "name": key,
                "size": str(len(blob)),
                "generation": str(self.generations.get(f"{bucket}/{key}", 1)),
            }
            return 200, json.dumps(meta).encode()
        raise AssertionError(f"unexpected url {url}")


@pytest.fixture()
def fake():
    return FakeGCS()


@pytest.fixture()
def client(fake):
    return storage.GCSClient(transport=fake.transport)


def test_path_helpers():
    assert storage.is_gcs("gs://b/k") and not storage.is_gcs("/tmp/x")
    assert storage.split_gcs("gs://bucket/a/b.csv") == ("bucket", "a/b.csv")
    assert storage.join("gs://b/p", "x", "y") == "gs://b/p/x/y"
    with pytest.raises(ValueError):
        storage.split_gcs("gs:///nope")


def test_round_trip_and_exists(client, fake):
    client.write_bytes("gs://est/data/curated.csv", b"a,b\n1,2\n")
    assert client.exists("gs://est/data/curated.csv")
    assert not client.exists("gs://est/data/other.csv")
    assert client.read_bytes("gs://est/data/curated.csv") == b"a,b\n1,2\n"
    with pytest.raises(FileNotFoundError):
        client.read_bytes("gs://est/data/other.csv")


def test_list_paginates(client, fake):
    for i in range(5):
        client.write_bytes(f"gs://est/reg/m/versions/1/f{i}", b"x")
    keys = client.list_keys("gs://est/reg/m/versions/")
    assert len(keys) == 5  # fake pages 2-at-a-time: pagination exercised
    assert any("pageToken" in c for c in fake.calls)


def test_dir_round_trip(client, tmp_path):
    (tmp_path / "sub").mkdir()
    (tmp_path / "a.txt").write_bytes(b"A")
    (tmp_path / "sub" / "b.txt").write_bytes(b"B")
    storage.upload_dir(tmp_path, "gs://est/bundles/v1", client)
    out = tmp_path / "out"
    storage.download_dir("gs://est/bundles/v1", out, client)
    assert (out / "a.txt").read_bytes() == b"A"
    assert (out / "sub" / "b.txt").read_bytes() == b"B"
    with pytest.raises(FileNotFoundError):
        storage.download_dir("gs://est/bundles/missing", out, client)


def test_ingest_reads_gcs_csv(client, monkeypatch):
    """load_csv_columns consumes the uploaded-dataset contract directly."""
    from mlops_tpu.data import generate_synthetic
    from mlops_tpu.data.ingest import load_csv_columns, write_csv_columns

    monkeypatch.setattr(storage, "_default_client", client)
    import io
    import tempfile
    from pathlib import Path

    columns, labels = generate_synthetic(50, seed=3)
    local = Path(tempfile.mkdtemp()) / "curated.csv"
    write_csv_columns(local, columns, labels)
    client.write_bytes("gs://est/data/curated.csv", local.read_bytes())

    got_cols, got_labels = load_csv_columns(
        "gs://est/data/curated.csv", require_target=True
    )
    assert got_cols.keys() == columns.keys()
    np.testing.assert_array_equal(got_labels, labels)
    assert got_cols["sex"] == columns["sex"]


def test_fetch_local_caches(client, fake, monkeypatch, tmp_path):
    from mlops_tpu.data.ingest import fetch_local

    monkeypatch.setattr(storage, "_default_client", client)
    client.write_bytes("gs://est/data/x.csv", b"hello")
    p1 = fetch_local("gs://est/data/x.csv", workdir=tmp_path)
    assert p1.read_bytes() == b"hello"
    downloads_before = sum("alt=media" in c for c in fake.calls)
    p2 = fetch_local("gs://est/data/x.csv", workdir=tmp_path)
    assert p2 == p1
    # Second fetch re-stats (cheap) but never re-downloads the media.
    assert sum("alt=media" in c for c in fake.calls) == downloads_before
    # A re-staged object at the same URI bumps the generation -> re-fetch.
    client.write_bytes("gs://est/data/x.csv", b"hello v2")
    p3 = fetch_local("gs://est/data/x.csv", workdir=tmp_path)
    assert p3 != p1 and p3.read_bytes() == b"hello v2"
    # local passthrough
    local = tmp_path / "y.csv"
    local.write_bytes(b"z")
    assert fetch_local(local) == local


def test_registry_on_gcs(client, tmp_path):
    """register -> resolve -> promote against the fake bucket."""
    from mlops_tpu.bundle.registry import ModelRegistry, parse_model_uri

    bundle = tmp_path / "bundle"
    bundle.mkdir()
    (bundle / "manifest.json").write_text(json.dumps({"flavor": "test"}))
    (bundle / "params.msgpack").write_bytes(b"\x01\x02")

    reg = ModelRegistry(
        "gs://est/registry", client=client, cache_dir=tmp_path / "cache"
    )
    uri = reg.register("credit", bundle, tags={"run": "r1"})
    assert uri == "models:/credit/1"
    assert parse_model_uri(uri) == ("credit", "1")
    uri2 = reg.register("credit", bundle)
    assert uri2 == "models:/credit/2"

    local = reg.resolve("credit", "latest")
    assert (local / "manifest.json").exists()
    assert (local / "params.msgpack").read_bytes() == b"\x01\x02"

    reg.set_stage("credit", 1, "production")
    prod = reg.resolve("credit", "production")
    assert prod.name == "1"
    versions = reg.list_versions("credit")
    assert [v["version"] for v in versions] == [1, 2]
    assert versions[0]["stage"] == "production"

    # A fresh registry object sees the same state (index lives in the bucket).
    reg2 = ModelRegistry(
        "gs://est/registry", client=client, cache_dir=tmp_path / "cache2"
    )
    assert reg2.resolve_uri("models:/credit/2")


def test_download_dir_prefix_is_exact(client, tmp_path):
    """versions/1 must not swallow versions/10 (digit-prefix siblings)."""
    client.write_bytes("gs://est/reg/m/versions/1/manifest.json", b"v1")
    client.write_bytes("gs://est/reg/m/versions/10/manifest.json", b"v10")
    out = storage.download_dir("gs://est/reg/m/versions/1", tmp_path / "v1", client)
    assert (out / "manifest.json").read_bytes() == b"v1"
    assert not (out / "0").exists()  # no version-10 bleed-through
    with pytest.raises(FileNotFoundError):
        storage.download_dir("gs://est/reg/m/versions/3", tmp_path / "v3", client)


def test_expired_token_refreshes_once(fake):
    """A 401 (metadata-server token expired mid-process) drops the cached
    token and retries once with a fresh one — long-lived serving
    replicas and >1h training jobs must survive token expiry."""
    state = {"expired": True}

    def transport(method, url, data, headers):
        if "metadata.google.internal" in url:
            token = "tok-2" if not state["expired"] else "tok-1"
            return 200, json.dumps({"access_token": token}).encode()
        if headers.get("Authorization") == "Bearer tok-1":
            state["expired"] = False  # server rejects the stale token
            return 401, b"{}"
        return fake.transport(method, url, data, headers)

    client = storage.GCSClient(transport=transport)
    client.write_bytes("gs://est/x", b"payload")  # first call: 401 -> refresh
    assert client.read_bytes("gs://est/x") == b"payload"
    assert client._token == "tok-2"


def test_registry_gcs_orphan_scan(client, tmp_path):
    """A crashed upload (objects, no index entry) can't collide."""
    from mlops_tpu.bundle.registry import ModelRegistry

    bundle = tmp_path / "bundle"
    bundle.mkdir()
    (bundle / "manifest.json").write_text("{}")
    client.write_bytes("gs://est/reg2/credit/versions/7/orphan.bin", b"x")
    reg = ModelRegistry(
        "gs://est/reg2", client=client, cache_dir=tmp_path / "cache"
    )
    assert reg.register("credit", bundle) == "models:/credit/8"


def test_ingest_reads_gcs_parquet(client, monkeypatch, tmp_path):
    """Parquet over gs:// rides the same generation-keyed fetch_local
    cache as CSV — both the batch reader and the streamed chunker."""
    pytest.importorskip("pyarrow")
    from mlops_tpu.data import generate_synthetic
    from mlops_tpu.data.parquet import write_parquet_columns
    from mlops_tpu.data.ingest import load_table_columns
    from mlops_tpu.data.stream import iter_table_chunks

    monkeypatch.setattr(storage, "_default_client", client)
    # Cache under tmp_path, not the real user cache: the fake bucket's
    # generation restarts at 1 every run, so the default ~/.cache key
    # would serve a PREVIOUS run's bytes and stop testing the roundtrip.
    from mlops_tpu.data import ingest as ingest_mod

    real_fetch = ingest_mod.fetch_local
    monkeypatch.setattr(
        ingest_mod,
        "fetch_local",
        lambda path, workdir=None: real_fetch(path, workdir=tmp_path / "cache"),
    )
    from mlops_tpu.data import parquet as parquet_mod

    monkeypatch.setattr(parquet_mod, "fetch_local", ingest_mod.fetch_local)
    columns, labels = generate_synthetic(60, seed=4)
    local = tmp_path / "curated.parquet"
    write_parquet_columns(local, columns, labels)
    client.write_bytes("gs://est/data/curated.parquet", local.read_bytes())

    got_cols, got_labels = load_table_columns(
        "gs://est/data/curated.parquet", require_target=True
    )
    np.testing.assert_array_equal(got_labels, labels)
    assert got_cols["sex"] == columns["sex"]

    sizes = [
        len(c["sex"])
        for c, _ in iter_table_chunks(
            "gs://est/data/curated.parquet", chunk_rows=25
        )
    ]
    assert sizes == [25, 25, 10]
