"""Training loop tests: learning happens, metrics parity, checkpoint resume."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlops_tpu.config import ModelConfig, TrainConfig
from mlops_tpu.data import Preprocessor, generate_synthetic
from mlops_tpu.models import build_model
from mlops_tpu.train import evaluate, fit
from mlops_tpu.train.metrics import binary_metrics, roc_auc


def test_roc_auc_matches_sklearn():
    pytest.importorskip("sklearn")
    from sklearn.metrics import roc_auc_score

    rng = np.random.default_rng(0)
    scores = rng.normal(size=500)
    labels = (rng.random(500) < 1 / (1 + np.exp(-scores + rng.normal(size=500)))).astype(
        int
    )
    ours = float(roc_auc(jnp.asarray(scores), jnp.asarray(labels)))
    ref = roc_auc_score(labels, scores)
    assert abs(ours - ref) < 1e-5


def test_roc_auc_with_ties():
    scores = jnp.asarray([0.1, 0.1, 0.1, 0.9, 0.9])
    labels = jnp.asarray([0, 0, 1, 1, 1])
    try:
        from sklearn.metrics import roc_auc_score

        ref = roc_auc_score(np.asarray(labels), np.asarray(scores))
    except ImportError:
        ref = 11 / 12  # hand-computed
    assert abs(float(roc_auc(scores, labels)) - ref) < 1e-6


def test_binary_metrics_names_and_ranges():
    logits = jnp.asarray([-2.0, -1.0, 1.0, 2.0])
    labels = jnp.asarray([0, 0, 1, 1])
    m = binary_metrics(logits, labels)
    assert set(m) == {"accuracy", "roc_auc", "f1", "precision", "recall"}
    assert float(m["accuracy"]) == 1.0
    assert float(m["roc_auc"]) == 1.0


def _train_tiny(steps=300, checkpoint_dir=None, seed=0):
    columns, labels = generate_synthetic(4000, seed=5)
    prep = Preprocessor.fit(columns)
    ds = prep.encode(columns, labels)
    split = int(0.8 * ds.n)
    train_ds, valid_ds = ds.slice(np.arange(split)), ds.slice(np.arange(split, ds.n))
    model = build_model(ModelConfig(family="mlp", hidden_dims=(64, 64), embed_dim=8))
    config = TrainConfig(
        batch_size=256,
        steps=steps,
        eval_every=100,
        checkpoint_every=100,
        learning_rate=3e-3,
        warmup_steps=20,
        seed=seed,
    )
    result = fit(
        model, train_ds, valid_ds, config, checkpoint_dir=checkpoint_dir
    )
    return model, result, valid_ds


def test_fit_learns_signal(tmp_path):
    model, result, valid_ds = _train_tiny(
        steps=300, checkpoint_dir=tmp_path / "ckpt"
    )
    # The synthetic process has strong signal; anything above 0.75 AUC means
    # the loop is actually learning (linear floor is ~0.80).
    assert result.metrics["validation_roc_auc_score"] > 0.75
    assert result.steps == 300
    # History carries the reference's five validation metric names.
    assert {
        "validation_accuracy_score",
        "validation_roc_auc_score",
        "validation_f1_score",
        "validation_precision_score",
        "validation_recall_score",
    } <= set(result.history[-1])
    # Checkpoints were written.
    assert (tmp_path / "ckpt" / "latest.json").exists()


def test_checkpoint_resume(tmp_path):
    # Train 200 steps with checkpointing, then "resume" a fresh fit with the
    # same config pointed at the same dir and 300 total steps: it should do
    # only the remaining 100.
    _train_tiny(steps=200, checkpoint_dir=tmp_path / "c")
    model, result, _ = _train_tiny(steps=300, checkpoint_dir=tmp_path / "c")
    assert result.steps == 300
    assert result.history[0]["step"] > 200  # resumed, not restarted


def test_step_budget_exact_when_not_window_aligned(tmp_path):
    # steps=250 with eval_every=100 must stop at exactly 250, not 300.
    model, result, _ = _train_tiny(steps=250)
    assert result.steps == 250


def test_ema_toggle_restore_mismatch_warns_loudly(tmp_path):
    """Toggling train.ema_decay between runs changes the TrainState pytree
    (the ema field appears/disappears), so existing checkpoints stop
    restoring — that must produce ONE warning NAMING the cause and the
    directory, never a silent restart from step 0 (ADVICE r5)."""
    import warnings as warnings_mod

    columns, labels = generate_synthetic(1500, seed=5)
    prep = Preprocessor.fit(columns)
    ds = prep.encode(columns, labels)
    train_ds, valid_ds = ds.slice(np.arange(1200)), ds.slice(np.arange(1200, ds.n))
    model = build_model(ModelConfig(family="mlp", hidden_dims=(16,), embed_dim=4))

    def cfg(ema):
        return TrainConfig(
            batch_size=128, steps=40, eval_every=20, checkpoint_every=20,
            ema_decay=ema,
        )

    fit(model, train_ds, valid_ds, cfg(0.0), checkpoint_dir=tmp_path / "c")
    with warnings_mod.catch_warnings(record=True) as caught:
        warnings_mod.simplefilter("always")
        result = fit(
            model, train_ds, valid_ds, cfg(0.99), checkpoint_dir=tmp_path / "c"
        )
    # Restarted from 0 (the mismatch is real)...
    assert result.history[0]["step"] <= 20
    # ...and said so ONCE, naming the ema toggle and the directory
    # (`train/checkpoint.py load_checkpoint` owns the message — a second
    # differently-worded warning for the same event would double-page).
    relevant = [
        str(w.message) for w in caught if "failed to restore" in str(w.message)
    ]
    assert len(relevant) == 1
    # The FULL config key: an operator greps the warning, finds the knob.
    assert "train.ema_decay" in relevant[0]
    assert str(tmp_path / "c") in relevant[0]


def test_checkpoint_survives_corrupt_pointer(tmp_path):
    _train_tiny(steps=200, checkpoint_dir=tmp_path / "c")
    (tmp_path / "c" / "latest.json").write_text("{torn")
    # Resume falls back to the newest readable ckpt file instead of crashing.
    model, result, _ = _train_tiny(steps=300, checkpoint_dir=tmp_path / "c")
    assert result.steps == 300
    assert result.history[0]["step"] > 200


def test_tensorboard_writer_emits_event_file(tmp_path):
    """train.tensorboard_dir streams the metrics.jsonl records as TF scalar
    events (SURVEY.md SS5.5 'jsonl + TensorBoard'); absence of the encoder
    degrades to a warning, never a training failure."""
    pytest.importorskip("torch.utils.tensorboard")
    from mlops_tpu.config import ModelConfig, TrainConfig
    from mlops_tpu.data import generate_synthetic, Preprocessor
    from mlops_tpu.models import build_model
    from mlops_tpu.train.loop import fit
    from mlops_tpu.train.pipeline import split_dataset

    columns, labels = generate_synthetic(1000, seed=3)
    pre = Preprocessor.fit(columns)
    ds = pre.encode(columns, labels)
    train_ds, valid_ds = split_dataset(ds, 0.2)
    config = TrainConfig(
        steps=20, eval_every=10, batch_size=128,
        tensorboard_dir=str(tmp_path / "tb"),
    )
    model = build_model(ModelConfig(family="linear"))
    fit(model, train_ds, valid_ds, config, metrics_path=tmp_path / "m.jsonl")
    events = list((tmp_path / "tb").glob("events.out.tfevents.*"))
    assert events and events[0].stat().st_size > 0


def test_tensorboard_writer_honored_by_layout_trainers(tmp_path):
    """The knob must work on EVERY trainer, not just fit (the silently-
    ignored-knob class): a TP layout run with tensorboard_dir set writes
    event files through the shared _metric_writers sink."""
    pytest.importorskip("torch.utils.tensorboard")
    from mlops_tpu.config import Config, ModelConfig
    from mlops_tpu.train.pipeline import run_layout_training

    config = Config()
    config.data.rows = 800
    config.model = ModelConfig(
        family="mlp", hidden_dims=(16,), dropout=0.0, precision="f32",
        tensor_parallel=2,
    )
    config.train.batch_size = 32
    config.train.steps = 2
    config.train.eval_every = 2
    config.train.distill_bulk = False
    config.train.tensorboard_dir = str(tmp_path / "tb")
    config.registry.run_root = str(tmp_path / "runs")
    config.registry.root = str(tmp_path / "reg")
    run_layout_training(config, register=False)
    events = list((tmp_path / "tb").glob("events.out.tfevents.*"))
    assert events and events[0].stat().st_size > 0


def test_ema_debias_matches_closed_form():
    """ema_t = d*ema + (1-d)*p from zeros; debiased by 1-d^t equals the
    geometrically-weighted average of the params seen so far."""
    import jax.numpy as jnp

    from mlops_tpu.train.loop import TrainState, ema_debiased

    d = 0.9
    params_seq = [1.0, 2.0, 5.0]
    ema = 0.0
    for p in params_seq:
        ema = d * ema + (1 - d) * p
    state = TrainState(
        params=None, opt_state=None,
        step=jnp.asarray(len(params_seq), jnp.int32),
        rng=jnp.zeros(2, jnp.uint32), ema=jnp.asarray(ema),
    )
    got = float(ema_debiased(state, d))
    weights = np.array([d**2 * (1 - d), d * (1 - d), (1 - d)])
    expect = float((weights * np.asarray(params_seq)).sum() / weights.sum())
    assert abs(got - expect) < 1e-6


def test_ema_training_serves_averaged_params(tmp_path):
    """With ema_decay on, the packaged params are the debiased average —
    different from the raw final params but still a working model."""
    from mlops_tpu.data import Preprocessor, generate_synthetic
    from mlops_tpu.models import build_model
    from mlops_tpu.train.loop import fit
    from mlops_tpu.train.pipeline import split_dataset

    columns, labels = generate_synthetic(2000, seed=6)
    pre = Preprocessor.fit(columns)
    ds = pre.encode(columns, labels)
    train_ds, valid_ds = split_dataset(ds, 0.2)
    model = build_model(ModelConfig(family="linear"))

    base = TrainConfig(steps=60, eval_every=30, batch_size=256)
    ema_cfg = TrainConfig(steps=60, eval_every=30, batch_size=256, ema_decay=0.9)
    raw = fit(model, train_ds, valid_ds, base)
    averaged = fit(model, train_ds, valid_ds, ema_cfg)
    assert np.isfinite(averaged.metrics["validation_roc_auc_score"])
    # same seed/schedule: raw params equal, so the EMA params must differ
    raw_leaf = jax.tree_util.tree_leaves(raw.params)[0]
    ema_leaf = jax.tree_util.tree_leaves(averaged.params)[0]
    assert raw_leaf.shape == ema_leaf.shape
    assert not np.allclose(raw_leaf, ema_leaf)


def test_ema_checkpoint_resume(tmp_path):
    """The EMA accumulator rides the checkpointed TrainState: a resumed
    run continues the average instead of restarting it."""
    from mlops_tpu.data import Preprocessor, generate_synthetic
    from mlops_tpu.models import build_model
    from mlops_tpu.train.loop import fit
    from mlops_tpu.train.pipeline import split_dataset

    columns, labels = generate_synthetic(1500, seed=8)
    pre = Preprocessor.fit(columns)
    ds = pre.encode(columns, labels)
    train_ds, valid_ds = split_dataset(ds, 0.2)
    model = build_model(ModelConfig(family="linear"))
    config = TrainConfig(
        steps=40, eval_every=20, batch_size=128, checkpoint_every=20,
        ema_decay=0.9, keep_best=False,  # isolate EMA from best-window selection
    )
    full = fit(model, train_ds, valid_ds, config, checkpoint_dir=tmp_path / "ck")
    # Re-fit from the final checkpoint: nothing left to train, so the
    # restored state (incl. ema) must reproduce the packaged params.
    resumed = fit(model, train_ds, valid_ds, config, checkpoint_dir=tmp_path / "ck")
    np.testing.assert_allclose(
        jax.tree_util.tree_leaves(full.params)[0],
        jax.tree_util.tree_leaves(resumed.params)[0],
        rtol=1e-6,
    )


def test_ema_metrics_describe_the_packaged_params(tmp_path):
    """The bundle metrics must grade the EMA params that ship, not the raw
    ones: the final history record's AUC equals a fresh eval of
    TrainResult.params."""
    from mlops_tpu.data import Preprocessor, generate_synthetic
    from mlops_tpu.models import build_model
    from mlops_tpu.train import evaluate
    from mlops_tpu.train.loop import fit
    from mlops_tpu.train.pipeline import split_dataset

    columns, labels = generate_synthetic(1500, seed=12)
    pre = Preprocessor.fit(columns)
    ds = pre.encode(columns, labels)
    train_ds, valid_ds = split_dataset(ds, 0.2)
    model = build_model(ModelConfig(family="linear"))
    config = TrainConfig(steps=40, eval_every=40, batch_size=128, ema_decay=0.9)
    result = fit(model, train_ds, valid_ds, config)
    fresh = evaluate(model, result.params, valid_ds)
    assert (
        abs(
            fresh["validation_roc_auc_score"]
            - result.metrics["validation_roc_auc_score"]
        )
        < 1e-6
    )


def test_mismatched_checkpoint_warns_instead_of_silent_restart(tmp_path):
    """Toggling ema_decay changes the TrainState pytree; resuming against
    old checkpoints must warn loudly, not silently restart from step 0."""
    from mlops_tpu.data import Preprocessor, generate_synthetic
    from mlops_tpu.models import build_model
    from mlops_tpu.train.loop import fit
    from mlops_tpu.train.pipeline import split_dataset

    columns, labels = generate_synthetic(1000, seed=13)
    pre = Preprocessor.fit(columns)
    ds = pre.encode(columns, labels)
    train_ds, valid_ds = split_dataset(ds, 0.2)
    model = build_model(ModelConfig(family="linear"))
    plain = TrainConfig(steps=20, eval_every=20, batch_size=128, checkpoint_every=10)
    fit(model, train_ds, valid_ds, plain, checkpoint_dir=tmp_path / "ck")
    with_ema = TrainConfig(
        steps=20, eval_every=20, batch_size=128, checkpoint_every=10,
        ema_decay=0.9,
    )
    with pytest.warns(UserWarning, match="failed to restore"):
        fit(model, train_ds, valid_ds, with_ema, checkpoint_dir=tmp_path / "ck")


def test_keep_best_packages_the_best_eval_window(tmp_path):
    """A run that degrades after its best eval window must package the best
    window's params+metrics (the measured 2400-step overfitting cliff:
    AUC 0.8056 -> 0.7537), never the final ones."""
    from mlops_tpu.data import Preprocessor, generate_synthetic
    from mlops_tpu.models import build_model
    from mlops_tpu.train import evaluate
    from mlops_tpu.train.loop import fit
    from mlops_tpu.train.pipeline import split_dataset

    columns, labels = generate_synthetic(1200, seed=15)
    pre = Preprocessor.fit(columns)
    ds = pre.encode(columns, labels)
    train_ds, valid_ds = split_dataset(ds, 0.25)
    # Tiny train split + many steps at high LR: guaranteed to overfit.
    model = build_model(ModelConfig(family="mlp", hidden_dims=(64, 64), dropout=0.0))
    config = TrainConfig(
        steps=400, eval_every=50, batch_size=256, learning_rate=2e-2,
        warmup_steps=10,
    )
    result = fit(model, train_ds, valid_ds, config)
    aucs = [r["validation_roc_auc_score"] for r in result.history]
    assert result.metrics["validation_roc_auc_score"] == max(aucs)
    # packaged params reproduce the packaged metrics
    fresh = evaluate(model, result.params, valid_ds)
    assert (
        abs(
            fresh["validation_roc_auc_score"]
            - result.metrics["validation_roc_auc_score"]
        )
        < 1e-6
    )
    # and keep_best=False would have shipped the (worse) final window
    final_auc = aucs[-1]
    assert result.metrics["validation_roc_auc_score"] >= final_auc


def test_keep_best_survives_checkpoint_resume(tmp_path):
    """The best-window snapshot persists next to the checkpoints: a
    resumed run that only degrades must still package the pre-resume
    best, not restart the comparison at -inf."""
    from mlops_tpu.data import Preprocessor, generate_synthetic
    from mlops_tpu.models import build_model
    from mlops_tpu.train.loop import fit
    from mlops_tpu.train.pipeline import split_dataset

    columns, labels = generate_synthetic(1200, seed=15)
    pre = Preprocessor.fit(columns)
    ds = pre.encode(columns, labels)
    train_ds, valid_ds = split_dataset(ds, 0.25)
    model = build_model(
        ModelConfig(family="mlp", hidden_dims=(64, 64), dropout=0.0)
    )

    def cfg(steps):
        return TrainConfig(
            steps=steps, eval_every=50, batch_size=256, learning_rate=2e-2,
            warmup_steps=10, checkpoint_every=50,
        )

    first = fit(model, train_ds, valid_ds, cfg(200), checkpoint_dir=tmp_path / "c")
    resumed = fit(model, train_ds, valid_ds, cfg(400), checkpoint_dir=tmp_path / "c")
    all_aucs = [
        r["validation_roc_auc_score"] for r in first.history + resumed.history
    ]
    assert resumed.metrics["validation_roc_auc_score"] == max(all_aucs)
    assert resumed.packaged_step <= 400
    assert resumed.steps == 400


def test_fit_does_not_consume_caller_init_variables(encoded_small):
    """Donation regression: run_window donates the TrainState, which used
    to DELETE caller-owned init buffers — a pretrained trunk reused for a
    second fine-tune run crashed with 'Array has been deleted'. fit must
    copy caller-provided init params into its own buffers."""
    import jax

    from mlops_tpu.models import build_model, init_params

    _, ds = encoded_small
    config = ModelConfig(family="mlp", hidden_dims=(16,), embed_dim=4)
    model = build_model(config)
    shared = init_params(model, jax.random.PRNGKey(0))
    # Same shared variables through two consecutive fits.
    tconfig = TrainConfig(steps=4, eval_every=4, batch_size=64)
    fit(model, ds, ds, tconfig, init_variables=shared)
    result = fit(model, ds, ds, tconfig, init_variables=shared)  # crashed
    assert np.isfinite(result.metrics["validation_roc_auc_score"])
    # The shared tree itself is still alive and usable.
    for leaf in jax.tree_util.tree_leaves(shared):
        np.asarray(leaf)
