"""compilecache: key invalidation, corruption recovery, hit/miss parity,
the donated-deserialize capability gate, registry sync, and the warmup CLI.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlops_tpu.compilecache import (
    CacheJob,
    CompileCache,
    donation_deserialize_safe,
    serialization_available,
)
from mlops_tpu.compilecache import keys
from mlops_tpu.compilecache.registry import CACHE_ENTRY_IDS

S = jax.ShapeDtypeStruct


@pytest.fixture(autouse=True, scope="module")
def _isolated_xla_cache():
    """Fully disable JAX's persistent compilation cache for this module:
    on jaxlib 0.4.x CPU an executable whose compile was SERVED from that
    cache (the suite's shared tests/.jax_cache — or even a fresh dir this
    module itself populated a few tests earlier) serializes into a broken
    "Symbols not found" artifact. cache.py validates round-trips and
    refuses those (see _persist), which would turn expected artifact-store
    hits below into 'unserializable' no-persists. The cache object latches
    on first use, so the flag flip alone is a no-op mid-process —
    reset_cache() forces re-initialization, after which the disabled flag
    is honored and every compile is real (and therefore serializable)."""
    try:
        from jax._src import compilation_cache as xla_cache
    except ImportError:  # private module moved on a newer jax: best effort
        xla_cache = None
    old = jax.config.jax_enable_compilation_cache
    if xla_cache is not None:
        xla_cache.reset_cache()
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    if xla_cache is not None:
        xla_cache.reset_cache()
    jax.config.update("jax_enable_compilation_cache", old)


needs_serialization = pytest.mark.skipif(
    not serialization_available(),
    reason="this jaxlib has no executable serialization (fallback mode)",
)


@pytest.fixture(scope="module")
def cc_pipeline(tmp_path_factory, _isolated_xla_cache):
    """A trained bundle with a model architecture UNIQUE to this module.

    Serving params are ARGUMENTS of the cached programs, so every engine
    over the same architecture compiles the same XLA program — and the
    session-shared warm_engine bundle's programs get disk-LOADED from the
    suite's persistent xla cache by other modules, which poisons their
    in-process re-serialization (see _isolated_xla_cache). A hidden-dims
    shape no other test uses keeps this module's programs out of that
    blast radius."""
    from mlops_tpu.config import Config, ModelConfig, TrainConfig
    from mlops_tpu.train.pipeline import run_training

    root = tmp_path_factory.mktemp("cc-pipeline")
    config = Config()
    config.data.rows = 2000
    config.model = ModelConfig(family="mlp", hidden_dims=(24,), embed_dim=4)
    config.train = TrainConfig(steps=30, eval_every=30, batch_size=128)
    config.registry.root = str(root / "registry")
    config.registry.run_root = str(root / "runs")
    return config, run_training(config)


def _double(x):
    return x * 2.0


def _job(entry="test-entry", dtype=jnp.float32, **kw):
    return CacheJob(
        entry_id=entry,
        jitted=jax.jit(_double),
        abstract_args=(S((4,), dtype),),
        **kw,
    )


# ----------------------------------------------------------------- registry
def test_cache_registry_matches_entry_point_registry():
    """The cache warms exactly the tpulint Layer-2 entry points — the two
    registries can never disagree about what the hot programs are."""
    from mlops_tpu.analysis.entrypoints import registered_entry_points
    from mlops_tpu.compilecache.warmup import _WARMERS

    names = {e.name for e in registered_entry_points()}
    assert names == set(CACHE_ENTRY_IDS)
    assert names == set(_WARMERS)


# --------------------------------------------------------------------- keys
def test_cache_key_invalidation_axes():
    """Every key axis produces a distinct digest: jax/jaxlib version bump,
    backend, model-config hash, mesh shape, donation flags, dtype/shape."""
    env = keys.environment_fingerprint()
    args = (S((4,), jnp.float32),)
    _, base = keys.cache_key("e", args, config_hash="m1", env=env)

    assert keys.cache_key("e", args, config_hash="m1", env=env)[1] == base
    variants = [
        keys.cache_key("e", args, config_hash="m1", env={**env, "jax": "9.9.9"})[1],
        keys.cache_key("e", args, config_hash="m1", env={**env, "jaxlib": "9.9.9"})[1],
        keys.cache_key("e", args, config_hash="m1", env={**env, "backend": "tpu"})[1],
        keys.cache_key("e", args, config_hash="m2", env=env)[1],
        keys.cache_key("e", args, config_hash="m1", mesh_shape=(2, 4), env=env)[1],
        keys.cache_key("e", args, config_hash="m1", donated=True, env=env)[1],
        keys.cache_key("e", (S((4,), jnp.int32),), config_hash="m1", env=env)[1],
        keys.cache_key("e", (S((8,), jnp.float32),), config_hash="m1", env=env)[1],
        keys.cache_key("other", args, config_hash="m1", env=env)[1],
    ]
    assert len({base, *variants}) == len(variants) + 1


def test_model_fingerprint_tracks_config():
    from mlops_tpu.config import ModelConfig

    a = keys.model_fingerprint(ModelConfig(hidden_dims=(8,)))
    b = keys.model_fingerprint(ModelConfig(hidden_dims=(16,)))
    assert a != b
    assert a == keys.model_fingerprint(ModelConfig(hidden_dims=(8,)))


# ----------------------------------------------------------- cache behavior
@needs_serialization
def test_miss_then_hit_bit_identical(tmp_path):
    c1 = CompileCache(tmp_path)
    fn1 = c1.load_or_compile(_job())
    assert c1.stats()["misses"] == 1 and c1.stats()["hits"] == 0
    assert c1.stats()["compile_s"] > 0

    c2 = CompileCache(tmp_path)  # second process, same dir
    fn2 = c2.load_or_compile(_job())
    s2 = c2.stats()
    assert s2["hits"] == 1 and s2["misses"] == 0
    assert s2["deserialize_s"] > 0

    x = np.arange(4, dtype=np.float32)
    assert np.array_equal(np.asarray(fn1(x)), np.asarray(fn2(x)))


@needs_serialization
def test_jax_version_bump_is_a_behavioral_miss(tmp_path, monkeypatch):
    CompileCache(tmp_path).load_or_compile(_job())
    real = keys.environment_fingerprint()
    monkeypatch.setattr(
        keys, "environment_fingerprint", lambda: {**real, "jax": "99.0.0"}
    )
    c2 = CompileCache(tmp_path)
    c2.load_or_compile(_job())
    assert c2.stats()["misses"] == 1 and c2.stats()["hits"] == 0


@needs_serialization
@pytest.mark.parametrize("corruption", ["truncate", "garbage", "flip"])
def test_corrupt_artifact_discarded_and_recompiled(tmp_path, corruption):
    """A damaged cache file can cost a recompile, never a crash and never
    a stale/garbled program."""
    c1 = CompileCache(tmp_path)
    c1.load_or_compile(_job())
    [artifact] = (tmp_path / "test-entry").glob("*.jaxexe")
    raw = artifact.read_bytes()
    if corruption == "truncate":
        artifact.write_bytes(raw[: len(raw) // 2])
    elif corruption == "garbage":
        artifact.write_bytes(b"not an executable at all")
    else:  # flip payload bytes: header parses, checksum must catch it
        artifact.write_bytes(raw[:-8] + bytes(8))

    c2 = CompileCache(tmp_path)
    fn = c2.load_or_compile(_job())
    s = c2.stats()
    assert s["discards"] == 1 and s["misses"] == 1 and s["hits"] == 0
    assert np.array_equal(
        np.asarray(fn(np.arange(4, dtype=np.float32))),
        np.arange(4, dtype=np.float32) * 2,
    )
    # The bad artifact was replaced by a valid one: third process hits.
    c3 = CompileCache(tmp_path)
    c3.load_or_compile(_job())
    assert c3.stats()["hits"] == 1


@pytest.mark.skipif(
    donation_deserialize_safe(),
    reason="donated deserialization is safe on this backend",
)
def test_donated_program_bypasses_cache_on_unsafe_backend(tmp_path):
    """Regression for the jaxlib 0.4.x CPU corruption: a donated program
    never reads OR writes the cache on this backend — it bypass-compiles,
    records the reason, and still runs correctly."""
    c = CompileCache(tmp_path)
    job = CacheJob(
        entry_id="donated-entry",
        jitted=jax.jit(_double, donate_argnums=(0,)),
        abstract_args=(S((4,), jnp.float32),),
        donated=True,
    )
    fn = c.load_or_compile(job)
    s = c.stats()
    assert s["bypasses"] == 1 and s["misses"] == 0 and s["hits"] == 0
    assert s["bypass_reasons"] == {"donated-deserialize-unsafe": 1}
    assert not list((tmp_path / "donated-entry").glob("*")) or not (
        tmp_path / "donated-entry"
    ).exists()
    out = np.asarray(fn(jnp.arange(4, dtype=jnp.float32)))
    assert np.array_equal(out, np.arange(4, dtype=np.float32) * 2)
    # Second process: still a bypass, never a deserialize.
    c2 = CompileCache(tmp_path)
    c2.load_or_compile(job)
    assert c2.stats()["bypasses"] == 1 and c2.stats()["hits"] == 0


# ------------------------------------------------------------ engine warmup
@needs_serialization
def test_engine_cold_then_warm_parity(tmp_path, cc_pipeline, monkeypatch):
    """The acceptance contract at unit scale: a second engine against a
    populated cache warms all-hits and serves BIT-IDENTICAL responses —
    bucketed and grouped paths both."""
    import mlops_tpu.serve.engine as engine_mod
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.serve.engine import InferenceEngine

    # Shrink the group grid so the test warms 2 bucket + 2 group programs.
    monkeypatch.setattr(engine_mod, "GROUP_SLOT_BUCKETS", (2,))
    monkeypatch.setattr(engine_mod, "GROUP_ROW_BUCKETS", (1, 8))

    _, result = cc_pipeline
    bundle = load_bundle(result.bundle_dir)
    cache_dir = tmp_path / "cc"

    e1 = InferenceEngine(
        bundle, buckets=(1, 8), compile_cache=CompileCache(cache_dir)
    )
    e1.warmup()
    s1 = e1.warmup_stats
    assert s1["programs"] == 4
    assert s1["cache"]["misses"] == 4 and s1["cache"]["hits"] == 0

    e2 = InferenceEngine(
        bundle, buckets=(1, 8), compile_cache=CompileCache(cache_dir)
    )
    e2.warmup()
    s2 = e2.warmup_stats
    assert s2["cache"]["hits"] == 4 and s2["cache"]["misses"] == 0

    rng = np.random.default_rng(3)
    cat = rng.integers(0, 2, (5, 9)).astype(np.int32)
    num = rng.normal(size=(5, 14)).astype(np.float32)
    assert e1.predict_arrays(cat, num) == e2.predict_arrays(cat, num)

    requests = [[_record()], [_record(), _record()]]
    assert e1.predict_group(requests) == e2.predict_group(requests)


def _record():
    from mlops_tpu.schema import LoanApplicant

    return LoanApplicant().model_dump()


@needs_serialization
def test_engine_without_cache_unchanged(cc_pipeline):
    """No cache configured: warmup still AOT-compiles (in parallel) and
    serves; responses match a cached engine's (the one-definition
    invariant across dispatch paths)."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.serve.engine import InferenceEngine

    _, result = cc_pipeline
    bundle = load_bundle(result.bundle_dir)
    engine = InferenceEngine(bundle, buckets=(1,), enable_grouping=False)
    engine.warmup()
    assert engine.ready
    assert engine.warmup_stats["cache"] is None
    out = engine.predict_arrays(
        np.zeros((1, 9), np.int32), np.zeros((1, 14), np.float32)
    )
    assert len(out["predictions"]) == 1


# ---------------------------------------------------------------- bulk path
@needs_serialization
def test_bulk_chunk_cache_hit_bit_identical(tmp_path, cc_pipeline):
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.parallel.bulk import make_chunk_scorer

    _, result = cc_pipeline
    bundle = load_bundle(result.bundle_dir)
    chunk = 128
    rng = np.random.default_rng(0)
    cat = rng.integers(0, 2, (chunk, 9)).astype(np.int8)
    num = rng.normal(size=(chunk, 14)).astype(np.float32)
    mask = np.arange(chunk) < 100

    c1 = CompileCache(tmp_path)
    s1 = make_chunk_scorer(
        bundle, mesh=None, exact=True, compile_cache=c1, chunk_rows=chunk
    )
    p1, f1 = s1(cat, num, mask)
    assert c1.stats()["misses"] >= 1

    c2 = CompileCache(tmp_path)
    s2 = make_chunk_scorer(
        bundle, mesh=None, exact=True, compile_cache=c2, chunk_rows=chunk
    )
    p2, f2 = s2(cat, num, mask)
    assert c2.stats()["hits"] >= 1 and c2.stats()["misses"] == 0
    assert np.array_equal(np.asarray(p1), np.asarray(p2))
    assert np.array_equal(np.asarray(f1), np.asarray(f2))

    # Novel shapes fall back to the jitted program instead of the cached
    # executable (which is shape-exact).
    small = 32
    p3, _ = s2(cat[:small], num[:small], np.ones(small, bool))
    assert np.asarray(p3).shape == (small,)


# ------------------------------------------------- warmup CLI + never-disagree
@needs_serialization
def test_warm_entry_points_then_engine_all_hits(tmp_path, cc_pipeline):
    """The ``warmup`` CLI body and the serving engine build keys through
    the SAME job builders: a cache pre-populated from the bundle makes a
    fresh engine warm with zero compiles."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.compilecache.warmup import warm_entry_points
    from mlops_tpu.serve.engine import InferenceEngine

    config, result = cc_pipeline
    bundle = load_bundle(result.bundle_dir)
    config.serve.warmup_batch_sizes = (1, 8)
    config.serve.batch_window_ms = 0.0  # skip the group grid (speed)
    config.score.chunk_rows = 128
    config.train.steps = 4
    config.train.eval_every = 4
    config.data.rows = 256

    cache = CompileCache(tmp_path)
    report = warm_entry_points(config, cache, bundle)
    assert set(report["entries"]) == set(CACHE_ENTRY_IDS)
    assert report["cache"]["hits"] == 0

    engine = InferenceEngine(
        bundle,
        buckets=(1, 8),
        enable_grouping=False,
        compile_cache=CompileCache(tmp_path),
    )
    engine.warmup()
    s = engine.warmup_stats["cache"]
    assert s["misses"] == 0 and s["hits"] == 2, (s, report["cache"])


@needs_serialization
def test_fit_with_cache_hits_on_second_run(tmp_path, encoded_small):
    """The dense train window rides the cache: a repeat run of the same
    config deserializes its scan instead of recompiling, and trains to
    bit-identical metrics."""
    from mlops_tpu.config import ModelConfig, TrainConfig
    from mlops_tpu.models import build_model
    from mlops_tpu.train.loop import fit
    from mlops_tpu.train.pipeline import split_dataset

    _, ds = encoded_small
    train_ds, valid_ds = split_dataset(ds, 0.2)
    mcfg = ModelConfig(family="mlp", hidden_dims=(8,), embed_dim=4)
    tcfg = TrainConfig(steps=6, eval_every=6, batch_size=64)

    c1 = CompileCache(tmp_path)
    r1 = fit(build_model(mcfg), train_ds, valid_ds, tcfg, compile_cache=c1)
    donated = any(
        p["source"] == "bypass-compiled" for p in c1.stats()["programs"].values()
    )
    if donated:
        pytest.skip("donation active on this backend: window bypasses cache")
    assert c1.stats()["misses"] == 1

    c2 = CompileCache(tmp_path)
    r2 = fit(build_model(mcfg), train_ds, valid_ds, tcfg, compile_cache=c2)
    assert c2.stats()["hits"] == 1 and c2.stats()["misses"] == 0
    assert r1.metrics == r2.metrics


def test_warmup_cli_config_mode(tmp_path, capsys):
    """`mlops-tpu warmup --cache-dir D <tiny overrides>` — no bundle
    anywhere — warms every entry point abstractly and reports JSON."""
    from mlops_tpu.cli import main

    rc = main(
        [
            "warmup",
            "--cache-dir",
            str(tmp_path),
            "model.hidden_dims=8",
            "model.embed_dim=4",
            "serve.warmup_batch_sizes=1",
            "serve.batch_window_ms=0",
            "score.chunk_rows=128",
            "train.steps=4",
            "train.eval_every=4",
            "data.rows=128",
        ]
    )
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["mode"] == "config"
    assert set(report["entries"]) == set(CACHE_ENTRY_IDS)
    assert report["programs"] >= 3
    assert report["cache"]["misses"] + report["cache"]["bypasses"] == (
        report["programs"]
    )
