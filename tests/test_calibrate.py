"""Temperature scaling (train/calibrate.py) — the calibration step the
reference never takes (`02-register-model.ipynb:330-353` serves raw
``predict_proba``)."""

import numpy as np

from mlops_tpu.train.calibrate import binary_nll, calibration_record, fit_temperature


def _overconfident_sample(true_t=2.5, n=20_000, seed=0):
    """Labels drawn from sigmoid(z/true_t) while the model reports z —
    i.e. the model is overconfident by a factor of true_t."""
    rng = np.random.default_rng(seed)
    z = rng.normal(scale=2.0, size=n)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z / true_t))).astype(np.float32)
    return z, y


def test_recovers_known_temperature():
    z, y = _overconfident_sample(true_t=2.5)
    t = fit_temperature(z, y)
    assert abs(t - 2.5) < 0.2


def test_calibration_never_hurts_nll():
    z, y = _overconfident_sample(true_t=3.0)
    record = calibration_record(z, y)
    assert record["val_nll_calibrated"] <= record["val_nll_uncalibrated"]
    # and for an already-calibrated model, T stays ~1
    z2, y2 = _overconfident_sample(true_t=1.0, seed=1)
    assert abs(fit_temperature(z2, y2) - 1.0) < 0.1


def test_degenerate_split_returns_identity():
    assert fit_temperature(np.array([]), np.array([])) == 1.0
    assert fit_temperature(np.ones(10), np.ones(10)) == 1.0  # single class


def test_nll_matches_closed_form():
    z = np.array([0.0, 10.0, -10.0])
    y = np.array([1.0, 1.0, 0.0])
    # softplus(0)-0 ~ ln2; the big-|z| correct cases contribute ~0
    assert abs(binary_nll(z, y) - np.log(2.0) / 3.0) < 1e-3


def test_bundle_carries_temperature_and_engine_applies_it(tiny_pipeline):
    """The pipeline fits T into the manifest and serving divides the
    logit by it — verified by reconstructing the raw logit."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.serve.engine import InferenceEngine

    config, result = tiny_pipeline
    bundle = load_bundle(result.bundle_dir)
    t = bundle.temperature
    assert t > 0
    assert bundle.manifest["calibration"]["temperature"] == round(t, 6)

    engine = InferenceEngine(bundle, buckets=(8,), enable_grouping=False)
    rng = np.random.default_rng(0)
    cat = rng.integers(0, 2, (3, bundle.preprocessor.cat_ids_shape[1])).astype(
        np.int32
    ) if hasattr(bundle.preprocessor, "cat_ids_shape") else rng.integers(
        0, 2, (3, 9)
    ).astype(np.int32)
    num = rng.normal(size=(3, 14)).astype(np.float32)
    served = np.asarray(engine.predict_arrays(cat, num)["predictions"])
    # Isolate the temperature mechanism: an identity-T engine over the SAME
    # bundle runs the identical jitted graph (an eager model.apply differs
    # by ~1e-3 of bf16 fusion noise and would drown the signal). Then
    # logit(served) must equal logit(uncalibrated) / T.
    import dataclasses as dc

    manifest_t1 = dict(bundle.manifest, calibration={})
    engine_t1 = InferenceEngine(
        dc.replace(bundle, manifest=manifest_t1), buckets=(8,),
        enable_grouping=False,
    )
    uncal = np.asarray(engine_t1.predict_arrays(cat, num)["predictions"])
    logit = lambda p: np.log(p) - np.log1p(-p)  # noqa: E731
    np.testing.assert_allclose(logit(served), logit(uncal) / t, atol=1e-4)


def test_old_manifest_without_calibration_defaults_to_identity(tiny_pipeline, tmp_path):
    import json
    import shutil

    from mlops_tpu.bundle import load_bundle

    _, result = tiny_pipeline
    legacy = tmp_path / "legacy"
    shutil.copytree(result.bundle_dir, legacy)
    manifest = json.loads((legacy / "manifest.json").read_text())
    manifest.pop("calibration", None)
    (legacy / "manifest.json").write_text(json.dumps(manifest))
    assert load_bundle(legacy).temperature == 1.0
