"""sloscope (ISSUE 14): SLO engine, flight recorder, cost ledger.

Covers the acceptance contracts: burn alerts flip within two evaluation
ticks; the SLO/alert series render identically on both planes (and keep
serving last-known values with ``engine_down`` raised through a full
engine outage); flight-recorder dumps are atomic (SIGKILL mid-write
never lands a torn file) and a clean plane writes ZERO of them; the
cost ledger round-trips monotone across runs, keys by entry + model
fingerprint, and ranks by cost_ms_per_row; build_info label sets are
identical across planes; log sampling never samples out a non-200.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

from mlops_tpu.config import Config, SLOConfig, SLOConfigError, ServeConfig
from mlops_tpu.slo import (
    CostLedger,
    FlightRecorder,
    SLOEngine,
    health_verdict,
    ledger_report,
    render_slo_lines,
)
from mlops_tpu.slo.engine import (
    ENGINE_ALERTS,
    read_slo_view,
    window_label,
    zero_view,
)

REPO = Path(__file__).resolve().parent.parent


def _fast_cfg(**overrides) -> SLOConfig:
    """Test-scale windows: seconds, not hours."""
    base = dict(
        enabled=True,
        fast_short_s=1.0,
        fast_long_s=2.0,
        slow_short_s=4.0,
        slow_long_s=8.0,
        tick_s=0.1,
        availability_target=0.999,
        latency_target=0.99,
        latency_threshold_ms=50.0,
    )
    base.update(overrides)
    return SLOConfig(**base).validate()


class _Counters:
    """A mutable cumulative counter source."""

    def __init__(self):
        self.good = 0
        self.total = 0

    def __call__(self):
        return {
            "default": (self.good, self.total, self.good, self.total)
        }


# ------------------------------------------------------------- SLO engine
def test_window_label_humanizes_round_windows():
    assert window_label(300) == "5m"
    assert window_label(3600) == "1h"
    assert window_label(21600) == "6h"
    assert window_label(259200) == "3d"
    assert window_label(7) == "7s"


def test_burn_alert_flips_within_two_ticks_and_clears():
    """The acceptance contract: counters crossing the burn threshold flip
    alert_active within two evaluation ticks; a recovered burn clears
    the fast alert once the short window drains."""
    src = _Counters()
    fired = []
    eng = SLOEngine(
        _fast_cfg(), ("default",), src,
        on_alert=lambda a, t, s: fired.append((a, t)),
    )
    t0 = time.monotonic()
    # Clean traffic: no alerts.
    src.good = src.total = 100
    eng.tick(t0 + 2.5)
    assert not eng.view()["default"]["alerts"]["availability_fast_burn"]
    assert not fired
    # A 504 storm: 50% bad — far past 14.4x the 0.1% budget.
    src.total = 200  # 100 bad
    eng.tick(t0 + 2.6)
    eng.tick(t0 + 2.7)  # within two ticks of the cross
    view = eng.view()
    assert view["default"]["alerts"]["availability_fast_burn"]
    assert ("availability_fast_burn", "default") in fired
    assert view["default"]["slos"]["availability"]["budget_pct"] < 0
    # Burn stops; once the fast windows drain past the bad interval the
    # fast alert clears (the short window is what ends alerts quickly).
    src.good = 10_200
    src.total = 10_300  # 10,100 good since — dilution plus window exit
    eng.tick(t0 + 6.0)
    assert not eng.view()["default"]["alerts"]["availability_fast_burn"]


def test_breaker_source_surfaces_as_alert_and_trigger():
    src = _Counters()
    fired = []
    breaker = {"default": False}
    eng = SLOEngine(
        _fast_cfg(), ("default",), src,
        breaker_source=lambda: breaker,
        on_alert=lambda a, t, s: fired.append(a),
    )
    eng.tick()
    assert not eng.view()["default"]["alerts"]["lifecycle_breaker"]
    breaker["default"] = True
    eng.tick()
    assert eng.view()["default"]["alerts"]["lifecycle_breaker"]
    assert fired == ["lifecycle_breaker"]
    eng.tick()  # sustained: no re-fire on a level, only on the edge
    assert fired == ["lifecycle_breaker"]


def test_zero_view_always_emits_every_series():
    """The always-emit contract: a fresh (or never-ticked) plane exports
    every SLO series at its zero baseline and every alert at 0."""
    cfg = _fast_cfg()
    lines = render_slo_lines(
        zero_view(("default",), (1.0, 2.0, 4.0, 8.0))
    )
    text = "\n".join(lines)
    for series in (
        'mlops_tpu_slo_good_total{slo="availability",tenant="default"} 0',
        'mlops_tpu_slo_total{slo="latency",tenant="default"} 0',
        'mlops_tpu_error_budget_remaining_pct{slo="availability",'
        'tenant="default"} 100.0',
        'mlops_tpu_slo_burn_rate{slo="availability",tenant="default",'
        'window="1s"} 0.0',
        'mlops_tpu_alert_active{alert="engine_down",severity="page",'
        'tenant="default"} 0',
    ):
        assert series in text, text
    for alert in ENGINE_ALERTS:
        assert f'alert="{alert}"' in text
    del cfg


def test_shm_mirror_round_trip_renders_identically():
    """Plane parity: the single-process engine's render and the ring
    render (write_rows -> read_slo_view) must produce byte-identical
    SLO blocks — the ONE-formatter discipline."""
    import numpy as np

    from mlops_tpu.slo.engine import N_ENGINE_ALERTS, SLO_FIELDS

    src = _Counters()
    src.good, src.total = 180, 200
    eng = SLOEngine(_fast_cfg(), ("default",), src)
    src.good, src.total = 380, 500
    eng.tick()
    direct = eng.render_lines()
    slo_vals = np.zeros((1, SLO_FIELDS))
    alert_vals = np.zeros((1, N_ENGINE_ALERTS))
    eng.write_rows(slo_vals, alert_vals)
    view = read_slo_view(
        slo_vals, alert_vals, ("default",), eng.windows
    )
    assert render_slo_lines(view) == direct


def test_health_verdict_states():
    view = zero_view(("default",), (1.0, 2.0, 4.0, 8.0))
    status, payload, _ = health_verdict(view, ready=True)
    assert (status, payload["verdict"]) == (200, "ok")
    view["default"]["alerts"]["availability_fast_burn"] = True
    status, payload, _ = health_verdict(view, ready=True)
    assert (status, payload["verdict"]) == (200, "degraded")
    assert payload["alerts"][0]["alert"] == "availability_fast_burn"
    status, payload, _ = health_verdict(view, ready=True, engine_down=True)
    assert (status, payload["verdict"]) == (503, "down")
    status, payload, _ = health_verdict(None, ready=False)
    assert (status, payload["verdict"]) == (503, "down")


def test_slo_config_validation_names_problems():
    with pytest.raises(SLOConfigError, match="availability_target"):
        SLOConfig(availability_target=1.0).validate()
    with pytest.raises(SLOConfigError, match="fast_short_s"):
        SLOConfig(fast_short_s=10.0, fast_long_s=5.0).validate()
    with pytest.raises(SLOConfigError, match="flightrec_keep"):
        SLOConfig(flightrec_keep=0).validate()
    # A threshold past the largest finite histogram edge would map to
    # +Inf and count every request as good — a silently dead alert.
    with pytest.raises(SLOConfigError, match="finite latency bucket"):
        SLOConfig(latency_threshold_ms=2000.0).validate()
    from mlops_tpu.serve.metrics import ServingMetrics

    SLOConfig(
        latency_threshold_ms=ServingMetrics.LATENCY_BUCKETS[-2]
    ).validate()  # the boundary itself is fine
    # Colliding window labels would overwrite each other's burn gauges.
    with pytest.raises(SLOConfigError, match="duplicate window labels"):
        SLOConfig(fast_short_s=90.0, fast_long_s=90.5).validate()


# ------------------------------------------------------- counter sources
def test_serving_metrics_slo_counts():
    from mlops_tpu.serve.metrics import ServingMetrics

    m = ServingMetrics()
    for status, latency in ((200, 1.0), (200, 80.0), (503, 0.2),
                            (504, 30000.0), (422, 1.0)):
        m.observe_request("/predict", status, latency)
    m.observe_request("/metrics", 200, 1.0)  # never SLO traffic
    counts = m.slo_counts(50.0, ("default",))
    good, total, lat_good, lat_total = counts["default"]
    # 422 counts as served (client error, no budget spend); 503/504 spend.
    assert (good, total) == (3, 5)
    # BOTH dimensions are /predict-scoped: the /metrics sample is
    # excluded (probe/scrape traffic must not dilute the latency SLO).
    # Threshold 50 -> good: 1.0, 0.2, 1.0; bad: 80 and 30000.
    assert (lat_good, lat_total) == (3, 5)


def test_ring_slo_counts_and_outage_render():
    """Ring twin of the counter source + the full-outage contract: with
    every replica down (supervisor-stamped) the scrape still renders —
    SLO gauges from the last-written rows, engine_down raised — and
    NEVER errors."""
    from mlops_tpu.serve.ipc import RequestRing, ShmWorkerMetrics
    from mlops_tpu.serve.metrics import render_ring_metrics

    ring = RequestRing(workers=2, slots_small=4, slots_large=1,
                       large_rows=8)
    cfg = _fast_cfg()
    ring.arm_slo(cfg)
    metrics = ShmWorkerMetrics(ring, 0)
    for status in (200, 200, 503, 504):
        metrics.observe_request("/predict", status, 1.0)
    good, total, lat_good, lat_total = ring.slo_counts(50.0)["default"]
    assert (good, total) == (2, 4)
    assert (lat_good, lat_total) == (4, 4)
    # The lead replica evaluates + mirrors:
    eng = SLOEngine(
        cfg, ring.tenant_names,
        source=lambda: ring.slo_counts(cfg.latency_threshold_ms),
    )
    for status in [503] * 40:
        metrics.observe_request("/predict", status, 1.0)
    eng.tick()
    eng.tick()
    eng.write_rows(ring.slo_vals, ring.alert_vals)
    # Now the full outage: every replica down, stamped.
    ring.set_ready(False)
    ring.eng_vals[0, 1] = time.monotonic()  # ENG_DOWN_SINCE
    text = render_ring_metrics(ring)
    assert (
        'mlops_tpu_alert_active{alert="engine_down",severity="page",'
        'tenant="default"} 1'
    ) in text
    assert (
        'mlops_tpu_alert_active{alert="availability_fast_burn",'
        'severity="page",tenant="default"} 1'
    ) in text
    # Last-known values, not zeros: the 503 flood (everything since the
    # engine armed — its construction-time sample is the baseline, so
    # the 4 pre-arm requests never bill) is still visible.
    assert 'mlops_tpu_slo_total{slo="availability",tenant="default"} 40' \
        in text


def test_respawned_evaluator_keeps_slo_totals_monotone():
    """ISSUE 11 discipline applied to sloscope: a respawned engine's
    fresh evaluator seeds from the dead incarnation's published shm
    rows, so the exported slo_good_total/slo_total never regress across
    a respawn (the chaos smoke's monotone-counter gate)."""
    from mlops_tpu.serve.ipc import RequestRing, ShmWorkerMetrics
    from mlops_tpu.slo.engine import SLO_NAMES

    ring = RequestRing(workers=1, slots_small=2, slots_large=1,
                       large_rows=8)
    cfg = _fast_cfg()
    ring.arm_slo(cfg)
    metrics = ShmWorkerMetrics(ring, 0)
    first = SLOEngine(
        cfg, ring.tenant_names,
        source=lambda: ring.slo_counts(cfg.latency_threshold_ms),
    )
    for status in (200,) * 50 + (503,) * 10:
        metrics.observe_request("/predict", status, 1.0)
    first.tick()
    first.write_rows(ring.slo_vals, ring.alert_vals)
    published = read_slo_view(
        ring.slo_vals, ring.alert_vals, ring.tenant_names, first.windows
    )["default"]["slos"]["availability"]
    assert published["total"] == 60
    # "kill -9": a successor evaluator boots against the SAME surviving
    # shm request counters, seeded with the published totals.
    prior = {
        "default": tuple(
            published_part
            for slo in SLO_NAMES
            for published_part in (
                read_slo_view(
                    ring.slo_vals, ring.alert_vals, ring.tenant_names,
                    first.windows,
                )["default"]["slos"][slo]["good"],
                read_slo_view(
                    ring.slo_vals, ring.alert_vals, ring.tenant_names,
                    first.windows,
                )["default"]["slos"][slo]["total"],
            )
        )
    }
    second = SLOEngine(
        cfg, ring.tenant_names,
        source=lambda: ring.slo_counts(cfg.latency_threshold_ms),
        prior_counts=prior,
    )
    second.tick()
    second.write_rows(ring.slo_vals, ring.alert_vals)
    after = read_slo_view(
        ring.slo_vals, ring.alert_vals, ring.tenant_names, second.windows
    )["default"]["slos"]["availability"]
    assert after["total"] >= published["total"]
    assert after["good"] >= published["good"]
    # New traffic keeps growing the continued counters.
    metrics.observe_request("/predict", 200, 1.0)
    second.tick()
    second.write_rows(ring.slo_vals, ring.alert_vals)
    grown = read_slo_view(
        ring.slo_vals, ring.alert_vals, ring.tenant_names, second.windows
    )["default"]["slos"]["availability"]
    assert grown["total"] == published["total"] + 1


def test_build_info_identical_label_set_across_planes():
    from mlops_tpu.serve.ipc import RequestRing
    from mlops_tpu.serve.metrics import (
        ServingMetrics,
        build_info_lines,
        render_ring_metrics,
    )

    line = build_info_lines()[1]
    assert line.startswith("mlops_tpu_build_info{backend=")
    for label in ("backend=", "jax=", "jaxlib=", "version="):
        assert label in line
    single = ServingMetrics().render()
    assert line in single
    ring = RequestRing(workers=1, slots_small=2, slots_large=1,
                       large_rows=8)
    ring_text = render_ring_metrics(ring)
    assert line in ring_text
    # The flight-dump counter rides the shared robustness block: zero
    # baseline on both planes (dumps are observable fleet-wide).
    assert "mlops_tpu_flightrec_dumps_total 0" in single
    assert "mlops_tpu_flightrec_dumps_total 0" in ring_text


# --------------------------------------------------------- flight recorder
def test_flightrec_spike_trigger_dumps_and_clean_ring_writes_nothing(
    tmp_path,
):
    rec = FlightRecorder(tmp_path, cooldown_s=0.0, spike_errors=5,
                         spike_window_s=10.0)
    for _ in range(20):
        rec.observe_request("/predict", 200, 1.0)
    assert list(tmp_path.glob("flightrec-*.json")) == []
    assert rec.dump_if_evidence("sigterm") is None  # clean drain: nothing
    for _ in range(5):
        rec.observe_request("/predict", 504, 30.0)
    # The triggered dump writes on a daemon thread (off the request
    # path): poll briefly for it to land.
    deadline = time.monotonic() + 5.0
    dumps: list = []
    while time.monotonic() < deadline and not dumps:
        dumps = list(tmp_path.glob("flightrec-*.json"))
        time.sleep(0.02)
    assert len(dumps) == 1
    payload = json.loads(dumps[0].read_text())
    assert payload["reason"] == "error_spike"
    statuses = [e["status"] for e in payload["events"]
                if e["kind"] == "request"]
    assert statuses.count(504) == 5


def test_flightrec_cooldown_bounds_dump_stream_and_retention(tmp_path):
    rec = FlightRecorder(tmp_path, cooldown_s=60.0, keep=2)
    writer = rec.trigger("one")
    assert writer is not None
    writer.join(timeout=10)
    assert rec.trigger("two") is None  # inside the cooldown
    assert rec.suppressed == 1
    rec2 = FlightRecorder(tmp_path, cooldown_s=0.0, keep=2)
    for i in range(5):
        writer = rec2.trigger(f"r{i}")
        assert writer is not None
        writer.join(timeout=10)  # serialize: retention is the subject
    assert len(list(tmp_path.glob("flightrec-*.json"))) == 2  # retention


def test_flightrec_alert_note_lands_in_timeline(tmp_path):
    rec = FlightRecorder(tmp_path, cooldown_s=0.0)
    rec.observe_request("/predict", 504, 31000.0, request_id="victim")
    rec.note_span({"kind": "span", "trace_id": "victim", "status": 504,
                   "entry": "bucket_8", "wall_ms": 31000.0,
                   "stages": {"dispatch": 30999.0}})
    rec.note_alert("availability_fast_burn", "default", "page")
    deadline = time.monotonic() + 5.0
    dumps: list = []
    while time.monotonic() < deadline and not dumps:
        dumps = list(tmp_path.glob("flightrec-*.json"))
        time.sleep(0.02)
    assert len(dumps) == 1
    path = dumps[0]
    from mlops_tpu.slo.flightrec import format_timeline, load_dump

    dump = load_dump(path)
    kinds = [e["kind"] for e in dump["events"]]
    assert kinds == ["request", "span", "alert"]
    timeline = format_timeline(dump)
    assert "victim" in timeline and "bucket_8" in timeline
    assert "availability_fast_burn" in timeline


def test_flightrec_failed_dump_keeps_evidence_and_cooldown(
    tmp_path, monkeypatch
):
    """A failed write (full disk mid-incident) must neither eat the
    evidence nor burn the cooldown: the next dump attempt retries and
    preserves the ring."""
    import mlops_tpu.slo.flightrec as fr

    rec = FlightRecorder(tmp_path, cooldown_s=60.0)
    rec.observe_request("/predict", 500, 1.0)
    real = fr.atomic_write

    def failing(path, data):
        raise OSError("disk full")

    monkeypatch.setattr(fr, "atomic_write", failing)
    assert rec.dump("incident") is None
    monkeypatch.setattr(fr, "atomic_write", real)
    # Evidence survived the failed write — the drain-time dump lands...
    assert rec.dump_if_evidence("sigterm") is not None
    # ...and the failed attempt's cooldown slot was restored (a fresh
    # trigger is not suppressed).
    rec.observe_request("/predict", 500, 1.0)
    assert rec.suppressed == 0


def test_slo_engine_sample_retention_stays_bounded():
    """Days of 1 s ticks must not grow per-tick work unboundedly: the
    per-tenant sample list caps (old half thins), and the burn math
    stays correct on the thinned history."""
    src = _Counters()
    cfg = _fast_cfg(slow_long_s=1e9, slow_short_s=1e8, tick_s=1.0)
    eng = SLOEngine(cfg, ("default",), src)
    t0 = time.monotonic()
    for i in range(9000):
        src.good = src.total = i * 10
        eng.tick(t0 + i)
    from mlops_tpu.slo.engine import _MAX_SAMPLES

    assert len(eng._samples["default"]) <= _MAX_SAMPLES
    # A burst of bad traffic still computes sane recent burns.
    src.total += 100  # 100 bad
    eng.tick(t0 + 9001)
    burn = eng.view()["default"]["slos"]["availability"]["burn"]["1s"]
    assert burn > 0


_FLIGHTREC_KILL = r"""
import sys
sys.path.insert(0, %(repo)r)
from mlops_tpu import faults
from mlops_tpu.slo.flightrec import FlightRecorder
faults.arm(faults.FaultPlan.from_rules(
    [{"point": "io.atomic_write.midwrite", "mode": "kill"}]
))
rec = FlightRecorder(%(dir)r, cooldown_s=0.0)
rec.observe_request("/predict", 500, 1.0)
rec.dump("chaos")  # SIGKILLs between tmp write and rename
"""


def test_flightrec_dump_survives_sigkill_midwrite(tmp_path):
    """The PR 9 persistence proof applied to dumps: SIGKILL between the
    tmp write and the rename (the exact window a sibling's kill -9 can
    land in) leaves NO torn flightrec-*.json — every landed dump
    parses, and the temp file never counts as a dump."""
    proc = subprocess.run(
        [sys.executable, "-c",
         _FLIGHTREC_KILL % {"repo": str(REPO), "dir": str(tmp_path)}],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]
    assert list(tmp_path.glob("flightrec-*.json")) == []
    # A second, unarmed run against the same dir dumps cleanly (the
    # leaked tmp never blocks the directory).
    rec = FlightRecorder(tmp_path, cooldown_s=0.0)
    rec.observe_request("/predict", 500, 1.0)
    assert rec.dump("after") is not None
    for dump in tmp_path.glob("flightrec-*.json"):
        json.loads(dump.read_text())  # every landed file parses


# ------------------------------------------------------------ cost ledger
def test_ledger_accumulates_monotone_across_runs(tmp_path):
    """Two 'serve runs' against one ledger dir: totals accumulate, never
    reset — the acceptance's monotone contract."""
    led = CostLedger(tmp_path, flush_interval_s=1000)
    led.observe("bucket_8", "aaaa1111", 5, 8, 0.002)
    led.observe("bucket_8", "aaaa1111", 3, 8, 0.001)
    led.close()
    first = json.loads((tmp_path / "ledger.json").read_text())
    assert first["entries"]["bucket_8@aaaa1111"]["dispatches"] == 2
    led2 = CostLedger(tmp_path, flush_interval_s=1000)
    led2.observe("bucket_8", "aaaa1111", 8, 8, 0.004)
    led2.close()
    second = json.loads((tmp_path / "ledger.json").read_text())
    entry = second["entries"]["bucket_8@aaaa1111"]
    assert entry["dispatches"] == 3
    assert entry["rows"] == 16
    assert entry["device_s"] >= first["entries"]["bucket_8@aaaa1111"][
        "device_s"
    ]


def test_ledger_keys_by_model_tag_never_cross_pollute(tmp_path):
    """A promotion to a different architecture (new model fingerprint)
    lands in a FRESH entry under the same shape name."""
    led = CostLedger(tmp_path, flush_interval_s=1000)
    led.observe("bucket_8", "aaaa1111", 8, 8, 0.010)
    led.observe("bucket_8", "bbbb2222", 8, 8, 0.001)
    led.close()
    report = ledger_report(tmp_path)
    keys = {row["key"] for row in report["entries"]}
    assert keys == {"bucket_8@aaaa1111", "bucket_8@bbbb2222"}
    # Ranked by cost_ms_per_row, most expensive first.
    assert report["entries"][0]["model"] == "aaaa1111"
    assert report["entries"][0]["cost_ms_per_row"] > report["entries"][1][
        "cost_ms_per_row"
    ]


def test_ledger_shm_mirror_and_merge(tmp_path):
    import numpy as np

    from mlops_tpu.slo.ledger import (
        TABLE_KEY_BYTES,
        TABLE_ROWS,
        TABLE_VALS,
        merge_entries,
        read_table,
        render_entry_lines,
    )

    led = CostLedger(tmp_path, flush_interval_s=1000)
    led.observe("group_16x1", "aaaa1111", 12, 16, 0.003)
    keys = np.zeros((TABLE_ROWS, TABLE_KEY_BYTES), np.uint8)
    vals = np.zeros((TABLE_ROWS, TABLE_VALS))
    led.write_table(keys, vals)
    led.close()
    entries = read_table(keys, vals)
    assert list(entries) == ["group_16x1@aaaa1111"]
    merged = merge_entries([entries, entries])
    assert merged["group_16x1@aaaa1111"][1] == 2  # dispatches add
    text = "\n".join(render_entry_lines(merged))
    assert (
        'mlops_tpu_entry_device_seconds_total{entry="group_16x1",'
        'model="aaaa1111"}'
    ) in text
    assert "mlops_tpu_entry_cost_ms_per_row" in text


def test_engine_ledger_hook_accounts_solo_and_grouped(
    warm_engine, sample_request, tmp_path
):
    """The engine-path integration: packed solo + grouped dispatches
    account device seconds under entry@fingerprint keys; disarmed the
    engine carries no hook state."""
    led = CostLedger(tmp_path, flush_interval_s=1000)
    warm_engine.set_cost_ledger(led)
    try:
        warm_engine.predict_records(sample_request * 3)  # bucket_8
        warm_engine.predict_group([sample_request, sample_request])
    finally:
        warm_engine.set_cost_ledger(None)
        led.close()
    report = ledger_report(tmp_path)
    by_entry = {row["entry"]: row for row in report["entries"]}
    assert "bucket_8" in by_entry
    group_entries = [e for e in by_entry if e.startswith("group_")]
    assert group_entries, by_entry
    tag = by_entry["bucket_8"]["model"]
    assert len(tag) == 8 and tag == warm_engine._cost_tag
    assert by_entry["bucket_8"]["device_s"] > 0
    assert by_entry["bucket_8"]["rows"] == 3
    assert by_entry["bucket_8"]["padded_rows"] == 8


# ------------------------------------------------------------ HTTP layer
class _StubShell:
    """Minimal HttpProtocol host for _predict-level tests."""

    def __new__(cls, config, score):
        from mlops_tpu.serve.httpcore import HttpProtocol
        from mlops_tpu.serve.metrics import ServingMetrics

        shell = HttpProtocol(config)
        shell.metrics = ServingMetrics()
        shell._score = score
        return shell


def test_log_sampling_always_logs_non_200s(caplog):
    """serve.log_sample_rate=0.01 under a shed burst: the sampled-out
    requests' InferenceData events are skipped, but EVERY 503 still
    logs its event (errors never sample out)."""

    async def shed_score(records, request_id, deadline=None, span=None,
                         tenant=0, slo=0):
        return (
            503, {"detail": "overloaded"}, "application/json",
            {"retry-after": "1"},
        )

    shell = _StubShell(
        ServeConfig(log_sample_rate=0.01).validate(), shed_score
    )
    body = json.dumps([{"credit_limit": 1000, "age": 30}]).encode()

    async def drive(n):
        results = []
        for i in range(n):
            results.append(
                await shell._predict(body, request_id=f"r{i}")
            )
        return results

    with caplog.at_level(logging.INFO, logger="mlops_tpu.serve"):
        results = asyncio.run(drive(50))
    assert all(r[0] == 503 for r in results)
    events = [r.getMessage() for r in caplog.records
              if "InferenceData" in r.getMessage()]
    assert len(events) == 50  # every shed logged despite rate 0.01


def test_log_sampling_samples_successes(caplog):
    async def ok_score(records, request_id, deadline=None, span=None,
                       tenant=0, slo=0):
        return {"predictions": [0.1], "outliers": [0],
                "feature_drift_batch": {}}

    shell = _StubShell(
        ServeConfig(log_sample_rate=0.01).validate(), ok_score
    )
    body = json.dumps([{"credit_limit": 1000, "age": 30}]).encode()

    async def drive(n):
        for i in range(n):
            await shell._predict(body, request_id=f"r{i}")

    with caplog.at_level(logging.INFO, logger="mlops_tpu.serve"):
        asyncio.run(drive(60))
    events = [r for r in caplog.records
              if "InferenceData" in r.getMessage()]
    # Statistically: 60 draws at p=0.01 — the chance of 20+ logs is
    # astronomically small; the assertion is "sampling happened".
    assert len(events) < 20


def test_log_sample_rate_validation():
    from mlops_tpu.config import ServeConfigError

    with pytest.raises(ServeConfigError, match="log_sample_rate"):
        ServeConfig(log_sample_rate=0.0).validate()
    with pytest.raises(ServeConfigError, match="log_sample_rate"):
        ServeConfig(log_sample_rate=1.5).validate()


def test_healthz_route_answers_verdict():
    """`GET /healthz` rides the shared router on every plane: the base
    protocol (no sloscope) answers from readiness alone."""
    from mlops_tpu.serve.httpcore import HttpProtocol
    from mlops_tpu.serve.metrics import ServingMetrics

    shell = HttpProtocol(ServeConfig().validate())
    shell.metrics = ServingMetrics()
    shell._ready = lambda: True

    async def drive():
        return await shell._route("GET", "/healthz", b"")

    status, payload, _ = asyncio.run(drive())
    assert status == 200 and payload["verdict"] == "ok"
    shell._ready = lambda: False
    status, payload, _ = asyncio.run(drive())
    assert status == 503 and payload["verdict"] == "down"


def test_frontend_healthz_and_slo_view_from_shm():
    """The ring plane's /healthz verdict reads the shm mirror: an armed
    ring with an active alert answers 'degraded'; a stamped full outage
    answers 503 'down'."""
    import numpy as np

    from mlops_tpu.serve.ipc import RequestRing
    from mlops_tpu.slo.engine import ENGINE_ALERTS as ALERTS

    ring = RequestRing(workers=1, slots_small=2, slots_large=1,
                       large_rows=8)
    cfg = _fast_cfg()
    ring.arm_slo(cfg)
    ring.slo_vals[0, 0] = 1.0  # HAS
    ring.alert_vals[0, ALERTS.index("availability_fast_burn")] = 1.0
    view = read_slo_view(
        ring.slo_vals, ring.alert_vals, ring.tenant_names,
        tuple(float(x) for x in ring.slo_meta[:4]),
    )
    status, payload, _ = health_verdict(view, ready=True)
    assert (status, payload["verdict"]) == (200, "degraded")
    ring.eng_vals[0, 1] = time.monotonic()  # ENG_DOWN_SINCE, not ready
    ring.set_ready(False)
    engine_down = not ring.engine_ready and bool(
        (np.asarray(ring.eng_vals[:, 1]) > 0).any()
    )
    status, payload, _ = health_verdict(
        view, ready=False, engine_down=engine_down
    )
    assert (status, payload["verdict"]) == (503, "down")


# ------------------------------------------------------------ trace-report
def test_load_spans_accepts_glob(tmp_path):
    from mlops_tpu.trace import load_spans

    for worker in (0, 1):
        with open(tmp_path / f"spans-w{worker}.jsonl", "w") as f:
            f.write(json.dumps({"kind": "span", "plane": "ring",
                                "worker": worker, "wall_ms": 1.0,
                                "stages": {"respond": 1.0}}) + "\n")
    spans = load_spans(tmp_path)  # dir form (existing)
    assert len(spans) == 2
    spans = load_spans(str(tmp_path / "spans-w*.jsonl"))  # glob form
    assert len(spans) == 2
    spans = load_spans(str(tmp_path / "spans-w1.jsonl"))  # file form
    assert len(spans) == 1


# ------------------------------------------------------- bench key contract
def test_bench_slo_stage_key_contract(warm_engine, sample_request):
    """BENCH_r08+ rounds carry the sloscope keys: disarmed-vs-armed
    batch-1 overhead plus the armed p50 (the documented armed delta)."""
    import bench

    out = bench._slo_stage(warm_engine, sample_request[0])
    assert set(out) >= {"slo_overhead_pct", "slo_armed_p50_ms"}
    assert isinstance(out["slo_overhead_pct"], float)
    assert out["slo_armed_p50_ms"] > 0
    # The stage restores the engine's disarmed state.
    assert warm_engine.cost_ledger is None
