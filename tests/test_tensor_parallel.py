"""DP×TP as a product config (`model.tensor_parallel`, VERDICT r4 #3):
the Megatron-laid-out sharded step reachable from `train`, with
checkpoint/resume, packaging, and serving — the same promotion PP/SP got
in round 4. Library-level sharding semantics live in test_parallel.py."""

import dataclasses
import json

import jax
import numpy as np
import pytest

from mlops_tpu.config import Config, ModelConfig


def _tp_config(tmp_path, steps=4, family="bert", **model_kw):
    config = Config()
    config.data.rows = 1500
    base = dict(
        family=family, token_dim=16, depth=2, heads=2, dropout=0.0,
        precision="f32", tensor_parallel=2,
    )
    if family == "mlp":
        base = dict(
            family="mlp", hidden_dims=(32, 32), dropout=0.0,
            precision="f32", tensor_parallel=2,
        )
    base.update(model_kw)
    config.model = ModelConfig(**base)
    config.train.batch_size = 32
    config.train.steps = steps
    config.train.eval_every = 100
    config.train.warmup_steps = 2
    config.train.checkpoint_every = 2
    config.train.distill_bulk = False
    config.registry.run_root = str(tmp_path / "runs")
    config.registry.root = str(tmp_path / "registry")
    return config


def test_tp_training_packages_servable_bundle(tmp_path):
    """`train` on a tensor_parallel config produces a NORMAL servable
    bundle: the params are the dense family tree (TP is a layout), and
    the full serving path answers the reference contract."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.schema import SCHEMA, LoanApplicant
    from mlops_tpu.serve.engine import InferenceEngine
    from mlops_tpu.train.pipeline import run_layout_training

    result = run_layout_training(_tp_config(tmp_path))
    assert result.model_uri and result.bundle_dir is not None
    assert (result.run_dir / "metrics.jsonl").exists()
    assert "validation_roc_auc_score" in result.train_result.metrics
    bundle = load_bundle(result.bundle_dir)
    assert bundle.manifest["tags"]["trained_with"].startswith(
        "tensor_parallel dp4xtp2"
    )
    cat = np.zeros((4, SCHEMA.num_categorical), np.int32)
    num = np.zeros((4, SCHEMA.num_numeric), np.float32)
    logits = bundle.model.apply(bundle.variables, cat, num, train=False)
    assert np.isfinite(np.asarray(logits)).all()
    engine = InferenceEngine(bundle, buckets=(1,), enable_grouping=False)
    response = engine.predict_records([LoanApplicant().model_dump()])
    assert set(response) == {"predictions", "outliers", "feature_drift_batch"}
    assert 0.0 <= response["predictions"][0] <= 1.0


def test_tp_moe_trains_expert_parallel_and_serves(tmp_path):
    """The EP stretch (VERDICT r4 #3): family=moe + tensor_parallel=K is
    expert parallelism as a PRODUCT config — the stacked expert weights
    shard over 'model' (PARAM_RULES 'experts_'), the run packages, and
    the bundle serves the single-record contract."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.schema import LoanApplicant
    from mlops_tpu.serve.engine import InferenceEngine
    from mlops_tpu.train.pipeline import run_layout_training
    from mlops_tpu.train.tensor_parallel import make_tp_trainer

    config = _tp_config(
        tmp_path, family="moe", num_experts=4, depth=1, heads=2,
    )
    # The expert axis really lands on 'model': check the trainer's own
    # shardings before the full run.
    trainer = make_tp_trainer(config)
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(
            trainer.shardings.params
        )[0]
    }
    expert_specs = [s.spec for name, s in flat.items() if "experts_in" in name]
    assert expert_specs and all("model" in str(sp) for sp in expert_specs)

    result = run_layout_training(config)
    assert result.model_uri and result.bundle_dir is not None
    bundle = load_bundle(result.bundle_dir)
    engine = InferenceEngine(bundle, buckets=(1,), enable_grouping=False)
    response = engine.predict_records([LoanApplicant().model_dump()])
    assert 0.0 <= response["predictions"][0] <= 1.0


def test_tp_training_resumes_from_checkpoint(tmp_path):
    """Preemption elasticity on the TP path: a re-invocation continues
    from the newest checkpoint (no duplicate metric rows), and the state
    restores onto the mesh layout."""
    from mlops_tpu.train.pipeline import run_layout_training

    run_layout_training(
        _tp_config(tmp_path, steps=2), register=False, run_name="tp-resume"
    )
    ckpt_dir = tmp_path / "runs" / "tp-resume" / "checkpoints"
    assert json.loads((ckpt_dir / "latest.json").read_text())["step"] == 2

    result = run_layout_training(
        _tp_config(tmp_path, steps=4), register=False, run_name="tp-resume"
    )
    assert json.loads((ckpt_dir / "latest.json").read_text())["step"] == 4
    lines = [
        json.loads(line)
        for line in (tmp_path / "runs" / "tp-resume" / "metrics.jsonl")
        .read_text()
        .splitlines()
    ]
    assert [rec["step"] for rec in lines] == [2, 4]
    assert result.bundle_dir is not None

    # Zero-step re-invocation still packages.
    again = run_layout_training(
        _tp_config(tmp_path, steps=4), register=False, run_name="tp-resume"
    )
    assert "validation_roc_auc_score" in again.train_result.metrics


def test_tp_training_matches_dense_loss_scale(tmp_path):
    """A TP=2 run and a dense run from the same seed/config land in the
    same loss regime — the layout must not change the math. (Exact
    equality is not expected: the dense path trains via fit's on-device
    minibatching; this pins gross equivalence through the product
    surface.)"""
    from mlops_tpu.train.pipeline import run_layout_training

    config = _tp_config(tmp_path, steps=6, family="mlp")
    result = run_layout_training(config, register=False, run_name="tp-mlp")
    auc = result.train_result.metrics["validation_roc_auc_score"]
    assert np.isfinite(auc) and auc > 0.5, auc


def test_tp_guards(tmp_path):
    from mlops_tpu.train.pipeline import run_layout_training
    from mlops_tpu.train.tensor_parallel import make_tp_trainer

    # Family without a Flax param tree.
    with pytest.raises(ValueError, match="Flax families"):
        make_tp_trainer(_tp_config(tmp_path, family="gbm"))

    # Device count not divisible by K.
    with pytest.raises(ValueError, match="multiple"):
        make_tp_trainer(_tp_config(tmp_path, tensor_parallel=3))

    # Batch must divide by the data axis (devices / K), with a named
    # error — not an opaque mid-run XLA sharding failure.
    bad_batch = _tp_config(tmp_path)
    bad_batch.train.batch_size = 30  # data axis is 8/2 = 4
    with pytest.raises(ValueError, match="batch_size"):
        make_tp_trainer(bad_batch)

    # Combined layout knobs refuse loudly at the entry point.
    config = _tp_config(tmp_path, tensor_parallel=2, pipeline_stages=2)
    with pytest.raises(ValueError, match="cannot combine"):
        run_layout_training(config)


def test_tp_trainer_starts_from_provided_dense_variables(tmp_path):
    """Pretrain → TP fine-tune: init_variables (e.g. a grafted masked-LM
    trunk) must become the TP trainer's starting point, same contract as
    the PP path — not a fresh init."""
    from mlops_tpu.models import build_model, init_params
    from mlops_tpu.train.tensor_parallel import make_tp_trainer

    config = _tp_config(tmp_path)
    dense_cfg = dataclasses.replace(config.model, tensor_parallel=0)
    provided = init_params(build_model(dense_cfg), jax.random.PRNGKey(99))
    trainer = make_tp_trainer(config, init_variables=provided)
    for a, b in zip(
        jax.tree.leaves(trainer.state.params),
        jax.tree.leaves(provided["params"]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_tp_with_ema_ships_averaged_params(tmp_path):
    """ema_decay>0 on the TP product path: trains, resumes, and the
    bundle's params differ from an identically-seeded raw run."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.train.pipeline import run_layout_training

    ema_cfg = _tp_config(tmp_path, steps=4)
    ema_cfg.train.ema_decay = 0.9
    ema = run_layout_training(ema_cfg, register=False, run_name="tp-ema")
    raw = run_layout_training(
        _tp_config(tmp_path, steps=4), register=False, run_name="tp-raw"
    )
    a = load_bundle(ema.bundle_dir).variables
    b = load_bundle(raw.bundle_dir).variables
    diffs = [
        float(np.abs(np.asarray(x) - np.asarray(y)).max())
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    ]
    assert max(diffs) > 1e-7, diffs
