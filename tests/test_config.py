"""Config tree tests: TOML + env + CLI override layering."""

import pytest

from mlops_tpu.config import Config, load_config


def test_defaults():
    config = load_config(env={})
    assert config.serve.port == 5000  # parity: app/Dockerfile EXPOSE 5000
    assert config.monitor.outlier_quantile == 0.95
    assert config.hpo.trials == 10  # parity: hyperopt max_evals=10
    # Removed dead knobs stay removed: drift_p_val (threshold consumption
    # lives in lifecycle.drift_threshold) and the mesh section (axis
    # layout is hardcoded in parallel/mesh.py). TPU503 regression pins.
    assert not hasattr(config.monitor, "drift_p_val")
    assert not hasattr(config, "mesh")


def test_toml_and_overrides(tmp_path):
    toml = tmp_path / "config.toml"
    toml.write_text(
        '[train]\nbatch_size = 512\n[model]\nfamily = "ft_transformer"\n'
        "hidden_dims = [64, 64]\n"
    )
    config = load_config(toml, overrides=["train.steps=42"], env={})
    assert config.train.batch_size == 512
    assert config.model.family == "ft_transformer"
    assert config.model.hidden_dims == (64, 64)
    assert config.train.steps == 42


def test_env_overrides():
    config = load_config(env={"MLOPS_TPU_SERVE_PORT": "8080"})
    assert config.serve.port == 8080


def test_architecture_specs_override_from_cli():
    """String tuples separate items on ';' (each spec contains commas);
    numeric tuples keep the ',' grammar."""
    config = load_config(
        overrides=[
            "hpo.architectures=hidden_dims=16;family=ft_transformer,token_dim=32",
            "model.hidden_dims=64,32",
        ],
        env={},
    )
    assert config.hpo.architectures == (
        "hidden_dims=16",
        "family=ft_transformer,token_dim=32",
    )
    assert config.model.hidden_dims == (64, 32)


def test_unknown_key_rejected():
    with pytest.raises(KeyError):
        load_config(overrides=["nope.nope=1"], env={})



def test_shipped_config_files_load_and_are_consistent():
    """Every configs/*.toml must parse into a valid Config; the structural
    sweep's specs must parse into ModelConfigs, and the long-context job's
    document length must be ring-shardable on a v5e-8 ('seq': 4)."""
    from pathlib import Path

    from mlops_tpu.train.hpo import parse_architecture_spec

    root = Path(__file__).resolve().parent.parent / "configs"
    files = sorted(root.glob("*.toml"))
    assert len(files) >= 3  # train_register, tune_architectures, long_context
    for path in files:
        if path.name == "tenants.toml":
            # The shipped tenant-fleet example is a tenants.toml document
            # (mlops_tpu/tenancy/), not a Config: validate its OWN shape
            # (bundle dirs are deployment-site paths, not checked here).
            from mlops_tpu.tenancy import load_tenants_toml

            fleet = load_tenants_toml(path).validate(check_bundles=False)
            assert len(fleet.tenants) >= 2
            assert fleet.default_tenant in fleet.names
            continue
        config = load_config(path, env={})
        assert config.data.valid_fraction <= 0.5
        for spec in config.hpo.architectures:
            parse_architecture_spec(spec, config.model)  # must not raise
        if config.model.seq_parallel:
            # Derive the doc length from the REAL model (a hardcoded
            # feature count would keep passing if SCHEMA grew and the
            # shipped config silently stopped ring-sharding on seq=4).
            import dataclasses

            from mlops_tpu.train.long_context import build_doc_model

            dense = dataclasses.replace(config.model, seq_parallel=False)
            seq = build_doc_model(dense).doc_seq_len
            assert seq % 4 == 0, (path.name, seq)
        if config.model.pipeline_stages:
            # The PP job must satisfy make_pp_train_step's invariants on
            # a v5e-8 mesh {'data': 2, 'stage': pipeline_stages}.
            s = config.model.pipeline_stages
            m = config.train.pipeline_microbatches
            assert config.model.depth % s == 0, path.name
            assert config.model.dropout == 0.0, path.name
            assert config.train.batch_size % m == 0, path.name
            assert (config.train.batch_size // m) % 2 == 0, path.name


def test_serve_drain_deadline_knobs_validate():
    """The hoisted drain/zygote deadlines (ISSUE 9: ex-hard-coded 30/35/50
    in serve/frontend.py) must reject inconsistent orderings by name."""
    from mlops_tpu.config import ServeConfig, ServeConfigError

    ServeConfig().validate()  # shipped defaults are consistent
    ServeConfig(
        drain_deadline_s=5.0,
        zygote_join_deadline_s=8.0,
        engine_zygote_join_s=15.0,
    ).validate()  # a fast chaos-scenario tuning is accepted
    import pytest as _pytest

    with _pytest.raises(ServeConfigError, match="drain_deadline_s"):
        ServeConfig(drain_deadline_s=0.0).validate()
    with _pytest.raises(ServeConfigError, match="zygote_join_deadline_s"):
        ServeConfig(
            drain_deadline_s=30.0, zygote_join_deadline_s=10.0
        ).validate()
    with _pytest.raises(ServeConfigError, match="engine_zygote_join_s"):
        ServeConfig(engine_zygote_join_s=36.0).validate()
    # ISSUE 11: the brownout 503 contract promises a positive respawn
    # ETA; zero/negative is rejected by name on the multi-worker plane.
    with _pytest.raises(ServeConfigError, match="engine_respawn_eta_s"):
        ServeConfig(workers=2, engine_respawn_eta_s=-1.0).validate()
    ServeConfig(workers=2, engine_respawn_eta_s=2.5).validate()


def test_serve_batching_and_tier_knobs_validate():
    """ISSUE 17 knobs: the hoisted micro-batcher geometry (batch_window_ms
    / max_group), the admission mode pair (batch_mode /
    batch_admit_fraction), and the serving tier selector are all rejected
    by name when inconsistent."""
    from mlops_tpu.config import ServeConfig, ServeConfigError

    ServeConfig().validate()  # shipped defaults are consistent
    ServeConfig(batch_mode="windowed", batch_window_ms=2.5).validate()
    ServeConfig(serve_tier="auto").validate()
    ServeConfig(batch_window_ms=0.0).validate()  # 0 = batching disabled
    with pytest.raises(ServeConfigError, match="batch_window_ms"):
        ServeConfig(batch_window_ms=-1.0).validate()
    with pytest.raises(ServeConfigError, match="max_group"):
        ServeConfig(max_group=1).validate()
    with pytest.raises(ServeConfigError, match="batch_mode"):
        ServeConfig(batch_mode="adaptive").validate()
    with pytest.raises(ServeConfigError, match="batch_admit_fraction"):
        ServeConfig(batch_admit_fraction=0.0).validate()
    with pytest.raises(ServeConfigError, match="batch_admit_fraction"):
        ServeConfig(batch_admit_fraction=1.5).validate()
    with pytest.raises(ServeConfigError, match="serve_tier"):
        ServeConfig(serve_tier="int8").validate()


def test_lifecycle_breaker_knobs_validate():
    from mlops_tpu.config import LifecycleConfig, LifecycleConfigError

    LifecycleConfig().validate()
    import pytest as _pytest

    with _pytest.raises(LifecycleConfigError, match="breaker_failures"):
        LifecycleConfig(breaker_failures=0).validate()
    with _pytest.raises(LifecycleConfigError, match="breaker_cooldown_s"):
        LifecycleConfig(breaker_cooldown_s=-1.0).validate()
