"""Config tree tests: TOML + env + CLI override layering."""

import pytest

from mlops_tpu.config import Config, load_config


def test_defaults():
    config = load_config(env={})
    assert config.serve.port == 5000  # parity: app/Dockerfile EXPOSE 5000
    assert config.monitor.drift_p_val == 0.05
    assert config.hpo.trials == 10  # parity: hyperopt max_evals=10


def test_toml_and_overrides(tmp_path):
    toml = tmp_path / "config.toml"
    toml.write_text(
        '[train]\nbatch_size = 512\n[model]\nfamily = "ft_transformer"\n'
        "hidden_dims = [64, 64]\n"
    )
    config = load_config(toml, overrides=["train.steps=42"], env={})
    assert config.train.batch_size == 512
    assert config.model.family == "ft_transformer"
    assert config.model.hidden_dims == (64, 64)
    assert config.train.steps == 42


def test_env_overrides():
    config = load_config(env={"MLOPS_TPU_SERVE_PORT": "8080"})
    assert config.serve.port == 8080


def test_unknown_key_rejected():
    with pytest.raises(KeyError):
        load_config(overrides=["nope.nope=1"], env={})

