"""Live 2-process DCN smoke: jax.distributed over localhost, one psum.

`parallel/distributed.py` claims a real multi-host handshake via the
``MLOPS_TPU_COORDINATOR`` env contract (what the GKE JobSet sets); this
test backs the claim with two actual OS processes on the CPU backend —
coordinator bring-up, Gloo peer connect, a cross-process ``psum`` through
``jax.shard_map``, and coordinator-only artifact gating. The reference
has nothing to test here (its "distributed" layer is HTTPS to managed
services, SURVEY.md §5.8); this is the TPU-native replacement's wire
check.
"""

import socket
import subprocess
import sys
from pathlib import Path

_WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")

from mlops_tpu.parallel.compat import shard_map
from mlops_tpu.parallel.distributed import initialize, is_coordinator

ran = initialize()
assert ran, "initialize() must run under MLOPS_TPU_COORDINATOR"
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.device_count()

import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("data",))
f = jax.jit(
    shard_map(
        lambda x: jax.lax.psum(x, "data"),
        mesh=mesh, in_specs=P("data"), out_specs=P(),
    )
)
rank = int(os.environ["MLOPS_TPU_PROCESS_ID"])
try:
    out = np.asarray(f(jnp.arange(2.0)))
    assert out.item() == 1.0, out
    psum = "ok"
except Exception as err:
    # jaxlib 0.4.x: "Multiprocess computations aren't implemented on the
    # CPU backend" — the DCN handshake above still proves the wire-up;
    # anything OTHER than that capability gap must fail the worker.
    if "Multiprocess computations" not in str(err):
        raise
    psum = "unsupported"
assert is_coordinator() == (rank == 0)
print(f"rank{{rank}} psum {{psum}}")
"""


def test_two_process_psum(tmp_path):
    repo = str(Path(__file__).resolve().parent.parent)
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER.format(repo=repo))

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    procs = []
    for rank in range(2):
        env = {
            "PATH": "/usr/bin:/bin:/usr/local/bin",
            "HOME": str(tmp_path),
            "JAX_PLATFORMS": "cpu",
            "MLOPS_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "MLOPS_TPU_PROCESS_ID": str(rank),
            "MLOPS_TPU_NUM_PROCESSES": "2",
        }
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker)],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
            )
        )
    outputs = []
    for rank, proc in enumerate(procs):
        out, _ = proc.communicate(timeout=180)
        outputs.append(out)
        assert proc.returncode == 0, f"rank{rank} failed:\n{out}"
    # Cross-process CPU collectives exist only from jax 0.5; on older
    # jaxlib the workers still prove the coordinator handshake and report
    # the capability gap explicitly.
    from mlops_tpu.parallel.compat import LEGACY_SHARD_MAP

    expected = "psum" if LEGACY_SHARD_MAP else "psum ok"
    for rank in range(2):
        assert f"rank{rank} {expected}" in outputs[rank]
