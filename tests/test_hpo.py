"""HPO tests: vmapped trials, mesh-sharded trials, best-trial selection."""

import dataclasses

import jax
import numpy as np
import pytest

from mlops_tpu.config import Config, HPOConfig, ModelConfig, TrainConfig
from mlops_tpu.data import Preprocessor, generate_synthetic
from mlops_tpu.parallel import make_mesh
from mlops_tpu.train.hpo import run_hpo, sample_hyperparams
from mlops_tpu.train.pipeline import run_tuning


@pytest.fixture(scope="module")
def splits():
    columns, labels = generate_synthetic(3000, seed=13)
    prep = Preprocessor.fit(columns)
    ds = prep.encode(columns, labels)
    idx = np.arange(ds.n)
    return ds.slice(idx[:2400]), ds.slice(idx[2400:])


def test_sample_hyperparams_deterministic():
    a = sample_hyperparams(HPOConfig(trials=8, seed=3))
    b = sample_hyperparams(HPOConfig(trials=8, seed=3))
    np.testing.assert_array_equal(a["learning_rate"], b["learning_rate"])
    assert (a["learning_rate"] > 0).all()
    assert a["pos_weight"].shape == (8,)


def test_run_hpo_selects_best(splits):
    train_ds, valid_ds = splits
    model_config = ModelConfig(family="mlp", hidden_dims=(32,), embed_dim=4)
    result = run_hpo(
        model_config,
        TrainConfig(batch_size=256),
        HPOConfig(trials=4, steps=60, seed=1),
        train_ds,
        valid_ds,
    )
    assert len(result.trials) == 4
    objectives = [
        t["metrics"]["validation_roc_auc_score"] for t in result.trials
    ]
    assert result.best_index == int(np.argmax(objectives))
    assert result.best_metrics["validation_roc_auc_score"] == max(objectives)
    # Winning params are a concrete single-trial pytree.
    import jax

    for leaf in jax.tree_util.tree_leaves(result.best_params):
        assert leaf.ndim >= 1 or leaf.shape == ()


def test_run_hpo_sharded_over_mesh_matches_unsharded(splits):
    train_ds, valid_ds = splits
    model_config = ModelConfig(
        family="mlp", hidden_dims=(32,), embed_dim=4, precision="f32"
    )
    tconfig = TrainConfig(batch_size=128)
    hconfig = HPOConfig(trials=8, steps=40, seed=2)
    mesh = make_mesh(8, model_parallel=1)
    sharded = run_hpo(
        model_config, tconfig, hconfig, train_ds, valid_ds, mesh=mesh
    )
    local = run_hpo(model_config, tconfig, hconfig, train_ds, valid_ds)
    # Same trials, same winner, metrics equal to float tolerance.
    assert sharded.best_index == local.best_index
    np.testing.assert_allclose(
        [t["metrics"]["validation_roc_auc_score"] for t in sharded.trials],
        [t["metrics"]["validation_roc_auc_score"] for t in local.trials],
        atol=1e-4,
    )


def test_run_sha_adaptive_sweep(splits):
    """Successive halving (hpo.strategy='sha'): completes within the
    random-search step budget, eliminates trials across rungs (recorded
    with the rung they died at), and the winner is a finalist whose
    params come from the continued (not restarted) training."""
    from mlops_tpu.train.hpo import run_sha

    train_ds, valid_ds = splits
    model_config = ModelConfig(
        family="mlp", hidden_dims=(32,), embed_dim=4, precision="f32"
    )
    hconfig = HPOConfig(
        trials=8, steps=40, seed=3, strategy="sha", eta=2, sha_rungs=3
    )
    result = run_sha(
        model_config, TrainConfig(batch_size=256), hconfig, train_ds, valid_ds
    )
    assert len(result.trials) == 8
    rungs = [t["rung"] for t in result.trials]
    # Eliminations happened: some died at rung 0, the winner reached 2.
    assert min(rungs) == 0 and max(rungs) == 2
    assert result.trials[result.best_index]["rung"] == 2
    assert np.isfinite(result.best_metrics["validation_roc_auc_score"])
    # Budget: sum over trials of steps-at-death <= trials*steps (equal
    # budget vs random), with the finalists carrying the most steps.
    # counts [8,4,2] -> rung_steps = 8*40//14 = 22.
    steps_spent = {t["steps"] for t in result.trials}
    assert max(steps_spent) == 3 * 22
    # run_hpo dispatches on the strategy field.
    via_dispatch = run_hpo(
        model_config, TrainConfig(batch_size=256), hconfig, train_ds, valid_ds
    )
    assert via_dispatch.best_index == result.best_index


def test_run_sha_sharded_matches_unsharded(splits):
    """The mesh path (trial axis over 'data', per-rung compiles) must
    reproduce the unsharded selection."""
    from mlops_tpu.train.hpo import run_sha

    train_ds, valid_ds = splits
    model_config = ModelConfig(
        family="mlp", hidden_dims=(32,), embed_dim=4, precision="f32"
    )
    tconfig = TrainConfig(batch_size=128)
    hconfig = HPOConfig(
        trials=8, steps=30, seed=4, strategy="sha", eta=2, sha_rungs=2
    )
    mesh = make_mesh(8, model_parallel=1)
    sharded = run_sha(
        model_config, tconfig, hconfig, train_ds, valid_ds, mesh=mesh
    )
    local = run_sha(model_config, tconfig, hconfig, train_ds, valid_ds)
    assert sharded.best_index == local.best_index
    np.testing.assert_allclose(
        sharded.best_metrics["validation_roc_auc_score"],
        local.best_metrics["validation_roc_auc_score"],
        atol=1e-4,
    )


def test_architecture_sweep_composes_with_sha(splits, tmp_path):
    """hpo.strategy='sha' must flow through the architecture-group
    driver: each group's inner sweep runs successive halving, the
    cross-group winner carries rung metadata, and group-granular resume
    caches the sha results too."""
    from mlops_tpu.train.hpo import run_architecture_hpo

    train_ds, valid_ds = splits
    base = ModelConfig(family="mlp", hidden_dims=(32,), embed_dim=4)
    hconfig = HPOConfig(
        trials=4, steps=30, seed=9, strategy="sha", eta=2, sha_rungs=2,
        architectures=("hidden_dims=16", "hidden_dims=32"),
    )
    win_cfg, result = run_architecture_hpo(
        base, TrainConfig(batch_size=256), hconfig, train_ds, valid_ds,
        resume_dir=tmp_path,
    )
    assert win_cfg.hidden_dims in ((16,), (32,))
    assert len(result.trials) == 8
    assert all("rung" in t for t in result.trials)
    assert (tmp_path / "hpo_groups" / "group_1.json").exists()


def test_hpo_rejects_unknown_strategy(splits):
    train_ds, valid_ds = splits
    with pytest.raises(ValueError, match="strategy"):
        run_hpo(
            ModelConfig(family="mlp", hidden_dims=(16,)),
            TrainConfig(batch_size=64),
            HPOConfig(trials=2, steps=5, strategy="tpe"),
            train_ds,
            valid_ds,
        )


def test_run_hpo_applies_ema(splits):
    """ema_decay>0 inside the vmapped sweep: the trials' returned params
    are the debiased Polyak average (not the raw tail), so selection
    grades what ships; metrics stay finite and the winner changes or
    matches — either way the run completes end-to-end."""
    train_ds, valid_ds = splits
    model_config = ModelConfig(
        family="mlp", hidden_dims=(32,), embed_dim=4, precision="f32"
    )
    hconfig = HPOConfig(trials=2, steps=40, seed=5)
    raw = run_hpo(
        model_config, TrainConfig(batch_size=256), hconfig, train_ds, valid_ds
    )
    ema = run_hpo(
        model_config,
        TrainConfig(batch_size=256, ema_decay=0.95),
        hconfig,
        train_ds,
        valid_ds,
    )
    assert np.isfinite(ema.best_metrics["validation_roc_auc_score"])
    # Same seeds/trials, different packaging: the EMA-averaged params
    # must differ from the raw final params.
    diffs = [
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(
            jax.tree_util.tree_leaves(raw.best_params),
            jax.tree_util.tree_leaves(ema.best_params),
        )
    ]
    assert max(diffs) > 1e-6, diffs


def test_run_tuning_packages_best(tmp_path):
    config = Config()
    config.data.rows = 2000
    config.model = ModelConfig(family="mlp", hidden_dims=(32,), embed_dim=4)
    config.train = TrainConfig(batch_size=256)
    config.hpo = HPOConfig(trials=2, steps=40)
    config.registry.root = str(tmp_path / "registry")
    config.registry.run_root = str(tmp_path / "runs")
    result, hpo_result = run_tuning(config)
    assert (result.bundle_dir / "manifest.json").exists()
    assert (result.run_dir / "trials.jsonl").exists()
    assert (result.run_dir / "best.json").exists()
    assert result.model_uri.startswith("models:/")
    # The packaged bundle serves.
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.serve.engine import InferenceEngine

    engine = InferenceEngine(
        load_bundle(result.bundle_dir), buckets=(1,), enable_grouping=False
    )
    out = engine.predict_records([{}])
    assert 0.0 <= out["predictions"][0] <= 1.0


def test_run_hpo_pads_trials_to_mesh_multiple(splits):
    """10 trials on an 8-device mesh: sharding engages via padding and the
    result still reports exactly 10 trials."""
    train_ds, valid_ds = splits
    result = run_hpo(
        ModelConfig(family="linear"),
        TrainConfig(batch_size=256),
        HPOConfig(trials=10, steps=30, seed=4),
        train_ds,
        valid_ds,
        mesh=make_mesh(8, model_parallel=1),
    )
    assert len(result.trials) == 10
    assert 0 <= result.best_index < 10
    assert np.isfinite(result.best_metrics["validation_roc_auc_score"])


def test_run_hpo_fewer_trials_than_devices(splits):
    """3 trials on an 8-device mesh: the pad amount (5) exceeds the trial
    count, which must cycle trials rather than under-pad and crash."""
    train_ds, valid_ds = splits
    result = run_hpo(
        ModelConfig(family="linear"),
        TrainConfig(batch_size=256),
        HPOConfig(trials=3, steps=30, seed=4),
        train_ds,
        valid_ds,
        mesh=make_mesh(8, model_parallel=1),
    )
    assert len(result.trials) == 3
    assert 0 <= result.best_index < 3


def test_sklearn_families_rejected_by_tune(splits):
    from mlops_tpu.config import Config

    config = Config()
    config.model.family = "gbm"
    with pytest.raises(ValueError, match="gbm"):
        run_tuning(config, register=False)


def test_run_hpo_never_selects_nan_trial(splits, monkeypatch):
    """A diverged (NaN-metric) trial must not win selection."""
    import mlops_tpu.train.hpo as hpo_mod

    real = hpo_mod.sample_hyperparams

    def poisoned(config):
        hp = real(config)
        hp["learning_rate"] = hp["learning_rate"].copy()
        hp["learning_rate"][0] = 1e6  # guaranteed divergence
        return hp

    monkeypatch.setattr(hpo_mod, "sample_hyperparams", poisoned)
    train_ds, valid_ds = splits
    result = run_hpo(
        ModelConfig(family="linear"),
        TrainConfig(batch_size=256),
        HPOConfig(trials=3, steps=40, seed=5),
        train_ds,
        valid_ds,
    )
    assert result.best_index != 0
    assert np.isfinite(result.best_metrics["validation_roc_auc_score"])


def test_parse_architecture_spec():
    from mlops_tpu.train.hpo import parse_architecture_spec

    base = ModelConfig()
    cfg = parse_architecture_spec(
        "family=mlp,hidden_dims=64x32,embed_dim=8", base
    )
    assert cfg.family == "mlp"
    assert cfg.hidden_dims == (64, 32)
    assert cfg.embed_dim == 8
    assert cfg.dropout == base.dropout  # untouched fields keep defaults
    with pytest.raises(ValueError, match="architecture spec"):
        parse_architecture_spec("not_a_field=3", base)
    with pytest.raises(ValueError, match="architecture spec"):
        parse_architecture_spec("hidden_dims", base)


def test_architecture_sweep_selects_across_groups(splits):
    """2-group structural sweep: the winner is the argmax over ALL trials of
    ALL groups (the reference's joint n_estimators/max_depth space,
    `01-train-model.ipynb:342-353`), and the returned ModelConfig is the
    winning group's."""
    from mlops_tpu.train.hpo import run_architecture_hpo

    train_ds, valid_ds = splits
    base = ModelConfig(family="mlp", hidden_dims=(32,), embed_dim=4)
    hconfig = HPOConfig(
        trials=2,
        steps=40,
        seed=7,
        architectures=("hidden_dims=16", "hidden_dims=32x16,embed_dim=8"),
    )
    win_cfg, result = run_architecture_hpo(
        base, TrainConfig(batch_size=256), hconfig, train_ds, valid_ds
    )
    assert len(result.trials) == 4  # 2 groups x 2 trials
    objectives = [
        t["metrics"]["validation_roc_auc_score"] for t in result.trials
    ]
    assert result.best_index == int(np.argmax(objectives))
    assert result.best_metrics["validation_roc_auc_score"] == max(objectives)
    # Structural choices surface alongside the continuous ones.
    assert result.best_hyperparams["family"] == "mlp"
    assert result.best_hyperparams["hidden_dims"] in ("16", "32x16")
    assert "learning_rate" in result.best_hyperparams
    # The winning config matches the surfaced structural record.
    want = (16,) if result.best_hyperparams["hidden_dims"] == "16" else (32, 16)
    assert win_cfg.hidden_dims == want
    # Every trial record names its group + architecture.
    assert {t["group"] for t in result.trials} == {0, 1}
    assert all("architecture" in t for t in result.trials)


def test_architecture_sweep_resumes_finished_groups(splits, tmp_path, monkeypatch):
    """Group-granular resume: with a resume_dir, a re-run restores every
    finished group from disk (run_hpo must NOT be called again) and
    reproduces the identical selection; a fingerprint change (different
    sweep budget) invalidates the cache and recomputes."""
    import mlops_tpu.train.hpo as hpo_mod
    from mlops_tpu.train.hpo import run_architecture_hpo

    train_ds, valid_ds = splits
    base = ModelConfig(family="mlp", hidden_dims=(32,), embed_dim=4)
    hconfig = HPOConfig(
        trials=2,
        steps=40,
        seed=7,
        architectures=("hidden_dims=16", "hidden_dims=32x16,embed_dim=8"),
    )
    tconfig = TrainConfig(batch_size=256)
    win_cfg, first = run_architecture_hpo(
        base, tconfig, hconfig, train_ds, valid_ds, resume_dir=tmp_path
    )
    assert (tmp_path / "hpo_groups" / "group_1.json").exists()

    def boom(*args, **kwargs):
        raise AssertionError("run_hpo recomputed a cached group")

    monkeypatch.setattr(hpo_mod, "run_hpo", boom)
    win_cfg2, second = run_architecture_hpo(
        base, tconfig, hconfig, train_ds, valid_ds, resume_dir=tmp_path
    )
    assert win_cfg2 == win_cfg
    assert second.best_index == first.best_index
    assert second.best_hyperparams == first.best_hyperparams
    for a, b in zip(
        jax.tree.leaves(first.best_params), jax.tree.leaves(second.best_params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # A changed sweep budget must invalidate the cache (and hit the boom).
    with pytest.raises(AssertionError, match="recomputed"):
        run_architecture_hpo(
            base,
            tconfig,
            dataclasses.replace(hconfig, steps=41),
            train_ds,
            valid_ds,
            resume_dir=tmp_path,
        )
    # So must an edit to a BASE model field no spec overrides (the
    # fingerprint hashes the full group config, not just the overrides).
    with pytest.raises(AssertionError, match="recomputed"):
        run_architecture_hpo(
            dataclasses.replace(base, dropout=0.05),
            tconfig,
            hconfig,
            train_ds,
            valid_ds,
            resume_dir=tmp_path,
        )
    # And so must CHANGED DATA of the same row count: the fingerprint
    # digests dataset content, not just train_ds.n.
    shuffled = dataclasses.replace(
        train_ds, numeric=np.ascontiguousarray(train_ds.numeric[::-1])
    )
    with pytest.raises(AssertionError, match="recomputed"):
        run_architecture_hpo(
            base, tconfig, hconfig, shuffled, valid_ds, resume_dir=tmp_path
        )


def test_architecture_sweep_empty_is_passthrough(splits):
    from mlops_tpu.train.hpo import run_architecture_hpo

    train_ds, valid_ds = splits
    base = ModelConfig(family="linear")
    hconfig = HPOConfig(trials=2, steps=30, seed=9)
    win_cfg, arch = run_architecture_hpo(
        base, TrainConfig(batch_size=256), hconfig, train_ds, valid_ds
    )
    plain = run_hpo(
        base, TrainConfig(batch_size=256), hconfig, train_ds, valid_ds
    )
    assert win_cfg == base
    assert arch.best_index == plain.best_index
    assert "family" not in arch.best_hyperparams  # unchanged contract


def test_run_tuning_packages_architecture_winner(tmp_path):
    """End-to-end: the packaged bundle's model config is the structural
    winner's, and it serves."""
    config = Config()
    config.data.rows = 2000
    config.model = ModelConfig(family="mlp", hidden_dims=(32,), embed_dim=4)
    config.train = TrainConfig(batch_size=256)
    config.hpo = HPOConfig(
        trials=2, steps=40, architectures=("hidden_dims=16", "hidden_dims=24")
    )
    config.registry.root = str(tmp_path / "registry")
    config.registry.run_root = str(tmp_path / "runs")
    result, hpo_result = run_tuning(config)
    assert hpo_result.best_hyperparams["hidden_dims"] in ("16", "24")

    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.serve.engine import InferenceEngine

    bundle = load_bundle(result.bundle_dir)
    want = (16,) if hpo_result.best_hyperparams["hidden_dims"] == "16" else (24,)
    assert tuple(bundle.model_config.hidden_dims) == want
    engine = InferenceEngine(bundle, buckets=(1,), enable_grouping=False)
    out = engine.predict_records([{}])
    assert 0.0 <= out["predictions"][0] <= 1.0
