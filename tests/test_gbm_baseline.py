"""CPU tree-ensemble baseline (BASELINE config 1) — trained, bundled, served
through the exact same interfaces as the Flax families."""

import json

import numpy as np
import pytest

from mlops_tpu.bundle import load_bundle
from mlops_tpu.config import Config, ModelConfig, TrainConfig
from mlops_tpu.models.gbm import SklearnBaseline
from mlops_tpu.serve import InferenceEngine
from mlops_tpu.train.pipeline import run_training


@pytest.fixture(scope="module")
def gbm_pipeline(tmp_path_factory):
    root = tmp_path_factory.mktemp("gbm")
    config = Config()
    config.data.rows = 3000
    config.model = ModelConfig(family="gbm", n_estimators=40, max_tree_depth=4)
    config.train = TrainConfig(seed=0)
    config.registry.root = str(root / "registry")
    config.registry.run_root = str(root / "runs")
    return config, run_training(config)


def test_gbm_trains_above_chance(gbm_pipeline):
    _, result = gbm_pipeline
    assert result.train_result.metrics["validation_roc_auc_score"] > 0.6


def test_gbm_bundle_flavor_and_round_trip(gbm_pipeline, encoded_small):
    _, result = gbm_pipeline
    manifest = json.loads((result.bundle_dir / "manifest.json").read_text())
    assert manifest["flavor"] == "sklearn"
    assert (result.bundle_dir / "estimator.joblib").exists()

    bundle = load_bundle(result.bundle_dir)
    assert bundle.flavor == "sklearn"
    assert bundle.model is None
    _, ds = encoded_small
    probs = bundle.estimator.predict_proba(ds.cat_ids[:64], ds.numeric[:64])
    assert probs.shape == (64,)
    assert ((probs >= 0) & (probs <= 1)).all()


def test_gbm_served_response_contract(gbm_pipeline, sample_request):
    """The floor model answers the reference's exact smoke-test payload with
    the reference's response schema (`app/model.py:64-70`) — interchangeable
    with the TPU bundles at the serving boundary."""
    _, result = gbm_pipeline
    engine = InferenceEngine(
        load_bundle(result.bundle_dir), buckets=(1, 8), enable_grouping=False
    )
    engine.warmup()
    out = engine.predict_records(sample_request)
    assert set(out) == {"predictions", "outliers", "feature_drift_batch"}
    assert len(out["predictions"]) == 1
    assert 0.0 <= out["predictions"][0] <= 1.0
    assert out["outliers"][0] in (0.0, 1.0)
    assert len(out["feature_drift_batch"]) == 23


def test_rf_family_reference_parity(encoded_small):
    """The reference's stock family (RandomForest) trains through the same
    wrapper (`01-train-model.ipynb:195-227`)."""
    _, ds = encoded_small
    model_config = ModelConfig(family="rf", n_estimators=30, max_tree_depth=6)
    baseline = SklearnBaseline.train(model_config, TrainConfig(seed=0), ds)
    metrics = baseline.evaluate(ds)
    assert metrics["validation_roc_auc_score"] > 0.6
    # serialization round-trip is exact
    clone = SklearnBaseline.from_bytes(baseline.to_bytes())
    np.testing.assert_array_equal(
        baseline.predict_proba(ds.cat_ids[:32], ds.numeric[:32]),
        clone.predict_proba(ds.cat_ids[:32], ds.numeric[:32]),
    )
