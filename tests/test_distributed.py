"""Multi-host bring-up logic (env detection only — real DCN needs hosts)."""

import jax

from mlops_tpu.parallel import distributed


def test_single_host_is_noop(monkeypatch):
    monkeypatch.delenv("MLOPS_TPU_COORDINATOR", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    assert distributed.multihost_env() is None
    assert distributed.initialize() is False


def test_explicit_env_contract(monkeypatch):
    monkeypatch.setenv("MLOPS_TPU_COORDINATOR", "10.0.0.1:8476")
    monkeypatch.setenv("MLOPS_TPU_PROCESS_ID", "3")
    monkeypatch.setenv("MLOPS_TPU_NUM_PROCESSES", "4")
    env = distributed.multihost_env()
    assert env == {
        "coordinator_address": "10.0.0.1:8476",
        "process_id": 3,
        "num_processes": 4,
    }


def test_coordinator_without_process_count_fails_fast(monkeypatch):
    """A coordinator with <2 processes is an inconsistent launch env;
    running on silently would train N divergent models."""
    import pytest

    monkeypatch.setenv("MLOPS_TPU_COORDINATOR", "10.0.0.1:8476")
    monkeypatch.setenv("MLOPS_TPU_NUM_PROCESSES", "1")
    with pytest.raises(ValueError, match="NUM_PROCESSES"):
        distributed.initialize()
    monkeypatch.delenv("MLOPS_TPU_NUM_PROCESSES", raising=False)
    with pytest.raises(ValueError, match="NUM_PROCESSES"):
        distributed.initialize()


def test_tpu_pod_env_uses_native_autodetect(monkeypatch):
    monkeypatch.delenv("MLOPS_TPU_COORDINATOR", raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
    assert distributed.multihost_env() == {}


def test_single_worker_hostnames_is_not_a_pod(monkeypatch):
    """1-host slices/dev containers export TPU_WORKER_HOSTNAMES=localhost;
    that must NOT trigger jax.distributed (its autodetect would fail)."""
    monkeypatch.delenv("MLOPS_TPU_COORDINATOR", raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    assert distributed.multihost_env() is None
    assert distributed.initialize() is False


def test_is_coordinator_single_host():
    assert distributed.is_coordinator() == (jax.process_index() == 0)
