"""Wire-bytes parity: `encode_response` must be byte-identical to the
dict path it replaced (`json.dumps(format_response(...),
separators=(",", ":")).encode()`).

The encode-residue optimization (ISSUE 18 satellite) moved response
serialization off the event loop by pre-encoding bytes in the executor —
but both serving planes' responses are contractually bit-identical, so
the splice encoder (one C json.dumps pass over the floats, static
skeleton baked at import) must reproduce the dict path's output
exactly. These tests pin that contract; the HTTP-level
parity suite (tests/test_frontend.py) re-proves it end to end through
real sockets. Jax-free: only serve/wire.py and the batcher's fallback
resolution are under test.
"""

from __future__ import annotations

import asyncio
import json
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from mlops_tpu.schema import SCHEMA
from mlops_tpu.serve.wire import (
    EMPTY_RESPONSE_BYTES,
    empty_response,
    encode_response,
    format_response,
)

D = len(SCHEMA.feature_names)


def _dict_bytes(p, o, d) -> bytes:
    return json.dumps(
        format_response(np.asarray(p), np.asarray(o), np.asarray(d)),
        separators=(",", ":"),
    ).encode()


@pytest.mark.parametrize("n", [1, 2, 8, 64, 256])
def test_encode_response_matches_dict_path(n):
    rng = np.random.default_rng(n)
    p = rng.standard_normal(n)
    o = rng.uniform(size=n)
    d = rng.standard_normal(D).round(6)  # the fetch contract: rounded f64
    assert encode_response(p, o, d) == _dict_bytes(p, o, d)


def test_encode_response_float_repr_edges():
    # Shortest-repr floats the encoder must match the dict path on:
    # sub-epsilon, negative zero, exact zero, integral floats, and values
    # whose repr needs all 17 digits.
    edge = [1e-07, -0.5, 0.0, -0.0, 1.0, 0.1 + 0.2, 1e300, 5e-324]
    p = np.array(edge)
    o = np.array(edge[::-1])
    d = np.resize(np.array(edge), D)
    assert encode_response(p, o, d) == _dict_bytes(p, o, d)


def test_encode_response_nonfinite_stays_identical():
    # A healthy fetch never produces these; because the floats ride the
    # SAME C encoder as the dict path, even degenerate NaN/Infinity
    # bytes are identical — no fallback branch to diverge.
    p = np.array([np.nan, 1.0])
    o = np.array([np.inf, -np.inf])
    d = np.zeros(D)
    assert encode_response(p, o, d) == _dict_bytes(p, o, d)


def test_empty_response_bytes_matches_dict():
    assert EMPTY_RESPONSE_BYTES == json.dumps(
        empty_response(), separators=(",", ":")
    ).encode()


def test_decoded_wire_bytes_equal_reference_dict():
    # The wire bytes must PARSE back to the reference response: keys in
    # schema order, every drift feature present.
    rng = np.random.default_rng(7)
    p, o = rng.uniform(size=3), rng.uniform(size=3)
    d = rng.standard_normal(D).round(6)
    decoded = json.loads(encode_response(p, o, d))
    assert decoded == format_response(p, o, d)
    assert list(decoded["feature_drift_batch"]) == list(SCHEMA.feature_names)


# ---------------------------------------------------- batcher resolution
class _DictOnlyStub:
    """Engine-API stub WITHOUT the wire methods: wire_responses=True must
    degrade to the dict path (the sklearn/stub contract)."""

    supports_grouping = False
    ready = True

    def predict_records(self, records, span=None):
        return {"predictions": [0.5] * len(records)}


class _WireStub(_DictOnlyStub):
    def predict_records_wire(self, records, span=None):
        return b'{"predictions":[0.5]}'


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_batcher_wire_mode_falls_back_without_wire_methods():
    from mlops_tpu.serve.batcher import MicroBatcher

    with ThreadPoolExecutor(max_workers=2) as pool:
        batcher = MicroBatcher(
            _DictOnlyStub(), pool, window_ms=0.0, wire_responses=True
        )
        out = _run(batcher.predict([{}]))
    assert out == {"predictions": [0.5]}


def test_batcher_wire_mode_prefers_wire_methods():
    from mlops_tpu.serve.batcher import MicroBatcher

    with ThreadPoolExecutor(max_workers=2) as pool:
        batcher = MicroBatcher(
            _WireStub(), pool, window_ms=0.0, wire_responses=True
        )
        out = _run(batcher.predict([{}]))
    assert out == b'{"predictions":[0.5]}'


def test_batcher_default_stays_on_dict_path():
    from mlops_tpu.serve.batcher import MicroBatcher

    with ThreadPoolExecutor(max_workers=2) as pool:
        batcher = MicroBatcher(_WireStub(), pool, window_ms=0.0)
        out = _run(batcher.predict([{}]))
    assert out == {"predictions": [0.5]}
