"""Native C++ encoder vs the pure-Python path: exact parity required."""

import numpy as np
import pytest

from mlops_tpu.data import Preprocessor
from mlops_tpu.data.ingest import write_csv_columns
from mlops_tpu import native


@pytest.fixture(scope="module")
def csv_file(tmp_path_factory):
    from mlops_tpu.data import generate_synthetic

    columns, labels = generate_synthetic(500, seed=11)
    path = tmp_path_factory.mktemp("native") / "data.csv"
    write_csv_columns(path, columns, labels)
    return path, columns, labels


def test_native_builds():
    assert native.native_available(), (
        "g++ is in the image; the native encoder must build"
    )


def test_native_matches_python_exactly(csv_file):
    path, columns, labels = csv_file
    prep = Preprocessor.fit(columns)
    got = native.encode_csv_native(path, prep, require_target=True)
    want = prep.encode(columns, labels)
    np.testing.assert_array_equal(got.cat_ids, want.cat_ids)
    np.testing.assert_allclose(got.numeric, want.numeric, atol=1e-5)
    np.testing.assert_array_equal(got.labels, np.asarray(want.labels, np.int8))


def test_native_handles_oov_missing_and_quotes(tmp_path):
    from mlops_tpu.schema import SCHEMA

    header = (
        ",".join(f.name for f in SCHEMA.categorical)
        + ","
        + ",".join(f.name for f in SCHEMA.numeric)
    )
    cat_row1 = ['"male"'] + ["NEVER_SEEN"] * (SCHEMA.num_categorical - 1)
    num_row1 = ["", "null"] + ["1.5"] * (SCHEMA.num_numeric - 2)
    path = tmp_path / "edge.csv"
    path.write_text(
        header + "\n" + ",".join(cat_row1 + num_row1) + "\n"
    )

    columns = {f.name: ["male"] for f in SCHEMA.categorical}
    for f in SCHEMA.numeric:
        columns[f.name] = [1.0]
    prep = Preprocessor.fit(columns)

    got = native.encode_csv_native(path, prep)
    assert got.labels is None
    assert got.cat_ids.shape == (1, SCHEMA.num_categorical)
    # Quoted "male" decodes to id 0; unseen values hit each feature's OOV id.
    assert got.cat_ids[0, 0] == 0
    for j, feat in enumerate(SCHEMA.categorical[1:], start=1):
        assert got.cat_ids[0, j] == feat.oov_id
    # Missing numerics -> median (=1.0) -> standardized 0 (std floor 1.0).
    np.testing.assert_allclose(got.numeric[0, :2], 0.0, atol=1e-6)


def test_native_missing_column_errors(tmp_path):
    path = tmp_path / "bad.csv"
    path.write_text("only_one_column\nx\n")
    columns = {"credit_limit": [1.0]}
    from mlops_tpu.schema import SCHEMA

    full = {f.name: ["male"] for f in SCHEMA.categorical}
    for f in SCHEMA.numeric:
        full[f.name] = [1.0]
    prep = Preprocessor.fit(full)
    with pytest.raises(ValueError, match="missing"):
        native.encode_csv_native(path, prep)


def test_fallback_path_matches(csv_file, monkeypatch):
    path, columns, labels = csv_file
    prep = Preprocessor.fit(columns)
    monkeypatch.setattr(native, "_lib_cache", False)
    got = native.encode_csv(path, prep, require_target=True)
    want = prep.encode(columns, labels)
    np.testing.assert_array_equal(got.cat_ids, want.cat_ids)
    np.testing.assert_allclose(got.numeric, want.numeric, atol=1e-5)


def _tiny_prep():
    from mlops_tpu.schema import SCHEMA

    columns = {f.name: ["male"] for f in SCHEMA.categorical}
    for f in SCHEMA.numeric:
        columns[f.name] = [1.0]
    return Preprocessor.fit(columns)


def _edge_csv(tmp_path, rows, header=None, name="edge.csv"):
    from mlops_tpu.schema import SCHEMA

    if header is None:
        header = ",".join(f.name for f in SCHEMA.categorical) + "," + ",".join(
            f.name for f in SCHEMA.numeric
        )
    path = tmp_path / name
    path.write_text(header + "\n" + "\n".join(rows) + "\n")
    return path


def _both_paths(path, prep, require_target=False):
    from mlops_tpu.data.ingest import load_csv_columns

    got = native.encode_csv_native(path, prep, require_target=require_target)
    columns, labels = load_csv_columns(path, require_target=require_target)
    want = prep.encode(columns, labels)
    np.testing.assert_array_equal(got.cat_ids, want.cat_ids)
    np.testing.assert_allclose(got.numeric, want.numeric, atol=1e-5)
    return got, want


def test_parity_stray_quote_and_garbage_numerics(tmp_path):
    """csv.reader semantics: mid-field quotes stay literal; float() ones:
    '1.5abc' and hex reject -> median. Native must match Python exactly."""
    from mlops_tpu.schema import SCHEMA

    cats = ['5\'6" tall'] + ["male"] * (SCHEMA.num_categorical - 1)
    nums = ["1.5abc", "0x1A"] + ["2.0"] * (SCHEMA.num_numeric - 2)
    path = _edge_csv(tmp_path, [",".join(cats + nums)])
    got, _ = _both_paths(path, _tiny_prep())
    # Both garbage numerics impute to the median (=1.0 -> standardized 0).
    np.testing.assert_allclose(got.numeric[0, :2], 0.0, atol=1e-6)


def test_parity_underscore_numeric_literals(tmp_path):
    """Python's float() accepts underscore separators between digits
    (float("1_000") == 1000.0) and rejects every other placement; the
    native parser must agree cell-for-cell."""
    from mlops_tpu.schema import SCHEMA

    cats = ["male"] * SCHEMA.num_categorical
    pad = ["2.0"] * (SCHEMA.num_numeric - 4)
    valid = "1_000"        # -> 1000.0
    bad_lead = "_1"        # -> median
    bad_trail = "1_"       # -> median
    bad_double = "1__0"    # -> median
    path = _edge_csv(
        tmp_path,
        [",".join(cats + [valid, bad_lead, bad_trail, bad_double] + pad)],
    )
    got, want = _both_paths(path, _tiny_prep())
    # Underscored thousands parse like the plain literal would; the three
    # malformed ones impute to the median (=1.0 -> standardized 0).
    np.testing.assert_allclose(got.numeric[0, 1:4], 0.0, atol=1e-6)
    assert float("1_000") == 1000.0  # the contract being mirrored


def test_parity_duplicate_header_last_wins(tmp_path):
    from mlops_tpu.schema import SCHEMA

    names = [f.name for f in SCHEMA.categorical] + [
        f.name for f in SCHEMA.numeric
    ]
    header = ",".join(names) + ",credit_limit"  # duplicate numeric column
    row = ",".join(
        ["male"] * SCHEMA.num_categorical
        + ["7.0"] * SCHEMA.num_numeric
        + ["9.0"]
    )
    path = _edge_csv(tmp_path, [row], header=header)
    prep = _tiny_prep()
    got, want = _both_paths(path, prep)
    # Last occurrence (9.0) must win on both paths.
    j = [f.name for f in SCHEMA.numeric].index("credit_limit")
    assert got.numeric[0, j] == want.numeric[0, j] == 9.0 - 1.0


def test_parity_cr_only_line_endings(tmp_path):
    from mlops_tpu.schema import SCHEMA

    header = ",".join(f.name for f in SCHEMA.categorical) + "," + ",".join(
        f.name for f in SCHEMA.numeric
    )
    row = ",".join(["male"] * SCHEMA.num_categorical + ["3.0"] * SCHEMA.num_numeric)
    path = tmp_path / "cr.csv"
    path.write_bytes((header + "\r" + row + "\r" + row + "\r").encode())
    got = native.encode_csv_native(path, _tiny_prep())
    assert got.cat_ids.shape[0] == 2


def test_corrupt_labels_fail_fast_both_paths(tmp_path):
    from mlops_tpu.data.ingest import load_csv_columns
    from mlops_tpu.schema import SCHEMA

    header = (
        ",".join(f.name for f in SCHEMA.categorical)
        + ","
        + ",".join(f.name for f in SCHEMA.numeric)
        + f",{SCHEMA.target}"
    )
    row = ",".join(
        ["male"] * SCHEMA.num_categorical
        + ["1.0"] * SCHEMA.num_numeric
        + ["oops"]
    )
    path = _edge_csv(tmp_path, [row], header=header)
    with pytest.raises(ValueError, match="target"):
        native.encode_csv_native(path, _tiny_prep(), require_target=True)
    with pytest.raises(ValueError, match="target"):
        load_csv_columns(path, require_target=True)


def test_blank_labels_on_scoring_path_mean_unlabeled(tmp_path):
    """Scoring files keeping an empty target column score fine (labels
    -> None) on BOTH paths; only require_target fails fast."""
    from mlops_tpu.data.ingest import load_csv_columns
    from mlops_tpu.schema import SCHEMA

    header = (
        ",".join(f.name for f in SCHEMA.categorical)
        + ","
        + ",".join(f.name for f in SCHEMA.numeric)
        + f",{SCHEMA.target}"
    )
    rows = [
        ",".join(["male"] * SCHEMA.num_categorical + ["1.0"] * SCHEMA.num_numeric + ["1"]),
        ",".join(["male"] * SCHEMA.num_categorical + ["1.0"] * SCHEMA.num_numeric + [""]),
    ]
    path = _edge_csv(tmp_path, rows, header=header)
    prep = _tiny_prep()
    got = native.encode_csv_native(path, prep)
    assert got.labels is None and got.cat_ids.shape[0] == 2
    _, labels = load_csv_columns(path)
    assert labels is None


hypothesis = pytest.importorskip("hypothesis")  # not in the CI dep list


class TestParityFuzz:
    """Property-based parity: for ANY ascii CSV content — quoted cells,
    garbage numerics, short rows, empties — the native kernel must encode
    bit-identically to the Python path (the contract every other native
    test pins pointwise; hypothesis explores the space)."""

    from hypothesis import given, settings
    from hypothesis import strategies as st

    _ascii = st.text(
        alphabet=st.characters(min_codepoint=32, max_codepoint=126),
        max_size=10,
    )
    _cat_cell = st.one_of(
        st.sampled_from(["male", "female", "university", "", "other"]),
        _ascii,
    )
    _num_cell = st.one_of(
        st.floats(
            allow_nan=False, allow_infinity=False, min_value=-1e30, max_value=1e30
        ).map(repr),
        _ascii,
        st.just(""),
    )
    _row = st.builds(
        lambda cats, nums, keep: (cats + nums)[: max(1, keep)],
        st.lists(_cat_cell, min_size=9, max_size=9),
        st.lists(_num_cell, min_size=14, max_size=14),
        st.integers(min_value=1, max_value=23),  # short rows included
    )

    @given(rows=st.lists(_row, min_size=1, max_size=12))
    @settings(max_examples=30, deadline=None)
    def test_fuzzed_csv_parity(self, rows):
        import csv as _csv
        import io
        import tempfile

        from mlops_tpu.data.ingest import load_csv_columns
        from mlops_tpu.schema import SCHEMA

        buf = io.StringIO()
        writer = _csv.writer(buf)
        writer.writerow(list(SCHEMA.feature_names))
        writer.writerows(rows)
        with tempfile.NamedTemporaryFile(
            "w", suffix=".csv", delete=False
        ) as f:
            f.write(buf.getvalue())
            path = f.name

        try:
            prep = _tiny_prep()
            got = native.encode_csv_native(path, prep)
            columns, labels = load_csv_columns(path)
            want = prep.encode(columns, labels)
            np.testing.assert_array_equal(got.cat_ids, want.cat_ids)
            np.testing.assert_allclose(
                got.numeric, want.numeric, atol=1e-4, rtol=1e-5
            )
        finally:
            import os as _os

            _os.unlink(path)
