"""Pipeline parallelism (parallel/pipeline.py): GPipe microbatch streaming
over a 'stage' mesh axis, validated exactly against the sequential fold."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlops_tpu.parallel.mesh import make_nd_mesh
from mlops_tpu.parallel.pipeline import make_pipeline


def _stage_fn(w, h):
    return jax.nn.gelu(h @ w[0] + w[1])


def _setup(stages, micro, batch=8, dim=16, seed=0):
    rng = np.random.default_rng(seed)
    weights = (
        jnp.asarray(rng.normal(scale=0.1, size=(stages, dim, dim)).astype(np.float32)),
        jnp.asarray(rng.normal(scale=0.1, size=(stages, dim)).astype(np.float32)),
    )
    x = jnp.asarray(rng.normal(size=(micro, batch, dim)).astype(np.float32))
    return weights, x


def _sequential(weights, x):
    out = x
    for s in range(weights[0].shape[0]):
        out = _stage_fn((weights[0][s], weights[1][s]), out)
    return out


@pytest.mark.parametrize("stages,micro", [(2, 4), (4, 8), (8, 8), (4, 1)])
def test_pipeline_matches_sequential_fold(stages, micro):
    mesh = make_nd_mesh({"stage": stages})
    weights, x = _setup(stages, micro)
    run = make_pipeline(mesh, _stage_fn)
    got = run(weights, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_sequential(weights, x)), atol=1e-5
    )


def test_pipeline_is_differentiable():
    """The scan + ppermute pipeline must transpose for training: gradients
    through the full pipeline equal gradients through the sequential fold."""
    mesh = make_nd_mesh({"stage": 4})
    weights, x = _setup(4, 4)
    run = make_pipeline(mesh, _stage_fn)

    g_pipe = jax.grad(lambda w: jnp.sum(run(w, x) ** 2))(weights)
    g_ref = jax.grad(lambda w: jnp.sum(_sequential(w, x) ** 2))(weights)
    np.testing.assert_allclose(
        np.asarray(g_pipe[0]), np.asarray(g_ref[0]), atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(g_pipe[1]), np.asarray(g_ref[1]), atol=1e-4
    )


def test_pipeline_composes_with_data_parallel_axis():
    """('data', 'stage') hybrid mesh: the pipeline must ignore extra mesh
    axes (inputs stay replicated — make_pipeline's in_specs are P() — so
    this covers axis coexistence, not a DP-sharded batch)."""
    mesh = make_nd_mesh({"data": 2, "stage": 4})
    weights, x = _setup(4, 4, batch=8)
    run = make_pipeline(mesh, _stage_fn)
    got = run(weights, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_sequential(weights, x)), atol=1e-5
    )


def test_pipeline_with_dp_sharded_batch():
    """batch_axis='data': the microbatch batch dim shards over 'data'
    while the ring still matches the sequential fold exactly."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_nd_mesh({"data": 2, "stage": 4})
    weights, x = _setup(4, 4, batch=8)
    x = jax.device_put(x, NamedSharding(mesh, P(None, "data")))
    run = make_pipeline(mesh, _stage_fn, batch_axis="data")
    got = run(weights, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_sequential(weights, x)), atol=1e-5
    )


# ------------------------- product path: train/pipeline_parallel.py ------


def _pp_configs(depth=4, batch=32, micro=4, family="bert"):
    from mlops_tpu.config import ModelConfig, TrainConfig

    model = ModelConfig(
        family=family,
        token_dim=32,
        depth=depth,
        heads=4,
        dropout=0.0,
        precision="f32",
        pipeline_stages=4,
    )
    train = TrainConfig(
        batch_size=batch,
        learning_rate=1e-3,
        steps=50,
        warmup_steps=2,  # the shared make_optimizer schedule: ramp fast so
        # the few-step loss-decrease assertion sees a real learning rate
        pipeline_microbatches=micro,
    )
    return model, train


def _pp_batch(n, seed=0):
    from mlops_tpu.schema import SCHEMA

    rng = np.random.default_rng(seed)
    cat = jnp.asarray(
        rng.integers(0, 2, (n, SCHEMA.num_categorical)).astype(np.int32)
    )
    num = jnp.asarray(rng.normal(size=(n, SCHEMA.num_numeric)).astype(np.float32))
    lab = jnp.asarray((rng.random(n) < 0.25).astype(np.float32))
    return cat, num, lab


@pytest.mark.parametrize("family", ["bert", "ft_transformer"])
def test_pp_forward_matches_dense(family):
    """The PP forward (embed → staged pipeline → head) must equal the
    dense model on the SAME params — pipeline parallelism is a layout,
    not a different model — for every supported trunk family."""
    from mlops_tpu.models import build_model, init_params
    from mlops_tpu.train.pipeline_parallel import (
        make_pp_train_step,
        split_trunk_params,
    )

    model_config, train_config = _pp_configs(family=family)
    mesh = make_nd_mesh({"data": 2, "stage": 4})
    trainer = make_pp_train_step(model_config, train_config, mesh, seed=7)

    dense = build_model(model_config)
    variables = init_params(dense, jax.random.PRNGKey(7))
    cat, num, _ = _pp_batch(train_config.batch_size)
    want = dense.apply(variables, cat, num, train=False)
    got = trainer.forward_fn(
        split_trunk_params(variables["params"], 4, family), cat, num
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_pp_train_step_decreases_loss():
    from mlops_tpu.train.pipeline_parallel import make_pp_train_step

    model_config, train_config = _pp_configs()
    mesh = make_nd_mesh({"data": 2, "stage": 4})
    trainer = make_pp_train_step(model_config, train_config, mesh)
    cat, num, lab = _pp_batch(train_config.batch_size)
    params, opt_state = trainer.params, trainer.opt_state
    losses = []
    for _ in range(8):
        params, opt_state, _, loss = trainer.step_fn(params, opt_state, trainer.ema, cat, num, lab)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


def test_pp_split_merge_roundtrip_and_packaging_parity():
    """merge(split(P)) == P, and a PP-trained tree converts back into a
    tree the DENSE model scores with — the packaging/serving path."""
    from mlops_tpu.models import build_model, init_params
    from mlops_tpu.train.pipeline_parallel import (
        make_pp_train_step,
        merge_bert_params,
        split_bert_params,
    )

    model_config, train_config = _pp_configs()
    dense = build_model(model_config)
    variables = init_params(dense, jax.random.PRNGKey(3))
    roundtrip = merge_bert_params(split_bert_params(variables["params"], 4))
    for a, b in zip(
        jax.tree.leaves(variables["params"]), jax.tree.leaves(roundtrip)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    mesh = make_nd_mesh({"data": 2, "stage": 4})
    trainer = make_pp_train_step(model_config, train_config, mesh)
    cat, num, lab = _pp_batch(train_config.batch_size)
    params, opt_state = trainer.params, trainer.opt_state
    params, _, _, _ = trainer.step_fn(params, opt_state, None, cat, num, lab)
    merged = merge_bert_params(jax.device_get(params))
    logits = dense.apply({"params": merged}, cat, num, train=False)
    assert np.isfinite(np.asarray(logits)).all()


def test_pp_starts_from_provided_dense_variables():
    """Pretrain → PP fine-tune: init_variables (a dense tree, e.g. a
    grafted masked-LM trunk) must become the trainer's starting point —
    embed/stage params equal the provided tree, not a fresh init."""
    from mlops_tpu.models import build_model, init_params
    from mlops_tpu.train.pipeline_parallel import make_pp_train_step

    model_config, train_config = _pp_configs()
    mesh = make_nd_mesh({"data": 2, "stage": 4})
    provided = init_params(build_model(model_config), jax.random.PRNGKey(99))
    trainer = make_pp_train_step(
        model_config, train_config, mesh, seed=0, init_variables=provided
    )
    np.testing.assert_array_equal(
        np.asarray(trainer.params["embed"]["tok_embed"]["embedding"]),
        np.asarray(provided["params"]["tok_embed"]["embedding"]),
    )
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(trainer.params["stages"])[0][0, 0]),
        np.asarray(jax.tree.leaves(provided["params"]["block_0"])[0]),
    )


def test_layout_training_rejects_init_params_where_unsupported(tmp_path):
    """Silent-ignore guards: doc runs cannot consume a pretrained trunk
    (pos_embed shape differs), and non-bert PP families share no trunk."""
    from mlops_tpu.config import Config, ModelConfig
    from mlops_tpu.train.pipeline import run_layout_training

    config = Config()
    config.data.rows = 400
    config.model = ModelConfig(
        family="bert", doc_records=3, token_dim=16, depth=1, heads=2,
        dropout=0.0, precision="f32",
    )
    config.train.init_params = str(tmp_path / "pre.msgpack")
    config.registry.run_root = str(tmp_path / "runs")
    with pytest.raises(ValueError, match="document training"):
        run_layout_training(config)

    config2 = Config()
    config2.data.rows = 400
    config2.model = ModelConfig(
        family="ft_transformer", token_dim=16, depth=4, heads=2,
        dropout=0.0, precision="f32", pipeline_stages=4,
    )
    config2.train.init_params = str(tmp_path / "pre.msgpack")
    config2.registry.run_root = str(tmp_path / "runs")
    with pytest.raises(ValueError, match="shares no trunk"):
        run_layout_training(config2)

    # pipeline_stages + doc_records/seq_parallel has no trainer: the PP
    # dispatch must not win silently and drop the document layout.
    config4 = Config()
    config4.data.rows = 400
    config4.model = ModelConfig(
        family="bert", doc_records=3, token_dim=16, depth=4, heads=2,
        dropout=0.0, precision="f32", pipeline_stages=4,
    )
    config4.registry.run_root = str(tmp_path / "runs")
    with pytest.raises(ValueError, match="cannot combine"):
        run_layout_training(config4)

    # ensemble_size>1 has no block_* trunk to split across stages; the
    # guard must name the combination, not die in split_trunk_params.
    from mlops_tpu.parallel import make_nd_mesh
    from mlops_tpu.train.pipeline_parallel import make_pp_train_step

    with pytest.raises(ValueError, match="ensemble_size"):
        make_pp_train_step(
            ModelConfig(
                family="bert", token_dim=16, depth=4, heads=2, dropout=0.0,
                precision="f32", pipeline_stages=4, ensemble_size=2,
            ),
            Config().train,
            make_nd_mesh({"stage": 4}),
        )

    # The DENSE path hits the same guard inside load_pretrained_variables
    # (an mlp graft would be a silent no-op — "fine-tuning" from fresh).
    from mlops_tpu.train.pipeline import run_training

    config3 = Config()
    config3.data.rows = 400
    config3.train.init_params = str(tmp_path / "pre.msgpack")
    config3.train.steps = 1
    config3.registry.run_root = str(tmp_path / "runs")
    with pytest.raises(ValueError, match="shares no trunk"):
        run_training(config3, register=False)


def test_pp_trains_at_bf16_like_the_shipped_config():
    """configs/pipeline_job.toml runs bf16 compute; one DP×PP step at
    that precision must produce a finite loss and keep param dtypes f32
    (params stay f32, compute casts — the zoo convention)."""
    import dataclasses

    from mlops_tpu.train.pipeline_parallel import make_pp_train_step

    model_config, train_config = _pp_configs()
    model_config = dataclasses.replace(model_config, precision="bf16")
    mesh = make_nd_mesh({"data": 2, "stage": 4})
    trainer = make_pp_train_step(model_config, train_config, mesh)
    cat, num, lab = _pp_batch(train_config.batch_size)
    params, _, _, loss = trainer.step_fn(
        trainer.params, trainer.opt_state, None, cat, num, lab
    )
    assert np.isfinite(float(loss))
    assert all(
        leaf.dtype == jnp.float32 for leaf in jax.tree.leaves(params)
    )


def test_pp_remat_changes_nothing_numerically():
    """train.pipeline_remat recomputes stage activations on backward
    (jax.checkpoint) — one step must produce the same params as without."""
    import dataclasses

    from mlops_tpu.train.pipeline_parallel import make_pp_train_step

    model_config, train_config = _pp_configs()
    mesh = make_nd_mesh({"data": 2, "stage": 4})
    cat, num, lab = _pp_batch(train_config.batch_size)
    results = []
    for remat in (False, True):
        trainer = make_pp_train_step(
            model_config,
            dataclasses.replace(train_config, pipeline_remat=remat),
            mesh,
            seed=11,
        )
        params, _, _, loss = trainer.step_fn(
            trainer.params, trainer.opt_state, None, cat, num, lab
        )
        results.append((jax.device_get(params), float(loss)))
    (p0, l0), (p1, l1) = results
    assert abs(l0 - l1) < 1e-6
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_pp_stage_params_shard_one_stage_per_device():
    """The memory claim behind PP: stage-stacked leaves shard their
    leading axis over 'stage' (each device holds depth/S blocks), the
    optimizer state inherits the layout, and a train step preserves it."""
    from mlops_tpu.train.pipeline_parallel import make_pp_train_step

    model_config, train_config = _pp_configs()
    mesh = make_nd_mesh({"data": 2, "stage": 4})
    trainer = make_pp_train_step(model_config, train_config, mesh)

    def leading_spec(leaf):
        return leaf.sharding.spec[0] if leaf.sharding.spec else None

    stage_leaf = jax.tree.leaves(trainer.params["stages"])[0]
    assert leading_spec(stage_leaf) == "stage"
    assert stage_leaf.addressable_data(0).shape[0] == 1  # one stage/device
    # adamw's mu/nu mirror the param layout (optax init preserves
    # sharding): check exactly the 'stages' subtrees, found structurally.
    adam_stage_leaves = []

    def visit(state):
        if hasattr(state, "mu"):
            adam_stage_leaves.extend(jax.tree.leaves(state.mu["stages"]))
            adam_stage_leaves.extend(jax.tree.leaves(state.nu["stages"]))
        elif isinstance(state, (tuple, list)):
            for sub in state:
                visit(sub)

    visit(trainer.opt_state)
    assert adam_stage_leaves  # the walk must actually find the adam state
    for leaf in adam_stage_leaves:
        assert leading_spec(leaf) == "stage", leaf.shape

    cat, num, lab = _pp_batch(train_config.batch_size)
    params, _, _, _ = trainer.step_fn(trainer.params, trainer.opt_state, None, cat, num, lab)
    assert leading_spec(jax.tree.leaves(params["stages"])[0]) == "stage"


def test_pp_config_validation():
    from mlops_tpu.config import ModelConfig
    from mlops_tpu.train.pipeline_parallel import make_pp_train_step

    model_config, train_config = _pp_configs()
    mesh = make_nd_mesh({"data": 2, "stage": 4})
    with pytest.raises(ValueError, match="depth"):
        make_pp_train_step(
            ModelConfig(**{**model_config.__dict__, "depth": 3}),
            train_config,
            mesh,
        )
    with pytest.raises(ValueError, match="dropout"):
        make_pp_train_step(
            ModelConfig(**{**model_config.__dict__, "dropout": 0.1}),
            train_config,
            mesh,
        )
    with pytest.raises(ValueError, match="stage"):
        make_pp_train_step(model_config, train_config, make_nd_mesh({"data": 8}))


def test_run_layout_training_pp_trains_and_packages_servable_bundle(tmp_path):
    """`train` on a pipeline_stages config must produce a NORMAL servable
    bert bundle: PP-trained stage-stacked params merge back to the dense
    tree and flow through the standard packaging tail."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.config import Config, ModelConfig
    from mlops_tpu.schema import SCHEMA
    from mlops_tpu.train.pipeline import run_layout_training

    config = Config()
    config.data.rows = 2000
    config.model = ModelConfig(
        family="bert", token_dim=16, depth=4, heads=2, dropout=0.0,
        precision="f32", pipeline_stages=4,
    )
    config.train.batch_size = 32
    config.train.steps = 6
    config.train.eval_every = 3
    config.train.warmup_steps = 2
    config.train.pipeline_microbatches = 4
    config.train.distill_bulk = False  # keep the test lean
    config.registry.run_root = str(tmp_path / "runs")
    config.registry.root = str(tmp_path / "registry")
    result = run_layout_training(config)

    assert result.model_uri and result.bundle_dir is not None
    assert (result.run_dir / "metrics.jsonl").exists()
    assert "validation_roc_auc_score" in result.train_result.metrics
    bundle = load_bundle(result.bundle_dir)
    cat = np.zeros((4, SCHEMA.num_categorical), np.int32)
    num = np.zeros((4, SCHEMA.num_numeric), np.float32)
    logits = bundle.model.apply(bundle.variables, cat, num, train=False)
    assert np.isfinite(np.asarray(logits)).all()
    # ...and through the REAL serving path: engine encode -> fused
    # classifier+drift+outlier -> reference response contract.
    from mlops_tpu.schema import LoanApplicant
    from mlops_tpu.serve.engine import InferenceEngine

    engine = InferenceEngine(bundle, buckets=(1,), enable_grouping=False)
    response = engine.predict_records([LoanApplicant().model_dump()])
    assert set(response) == {"predictions", "outliers", "feature_drift_batch"}
    assert 0.0 <= response["predictions"][0] <= 1.0


def test_run_layout_training_pp_resumes_from_checkpoint(tmp_path):
    """Preemption elasticity for layout runs (SURVEY §5.4): a second
    invocation of the same run resumes from the newest checkpoint instead
    of restarting at step 1, and a fully-complete run re-invoked runs
    zero steps but still packages."""
    import json

    from mlops_tpu.config import Config, ModelConfig
    from mlops_tpu.train.pipeline import run_layout_training

    def make_config(steps):
        config = Config()
        config.data.rows = 1500
        config.model = ModelConfig(
            family="bert", token_dim=16, depth=4, heads=2, dropout=0.0,
            precision="f32", pipeline_stages=4,
        )
        config.train.batch_size = 16
        config.train.steps = steps
        config.train.eval_every = 100  # evals only at the final step
        config.train.warmup_steps = 2
        config.train.checkpoint_every = 2
        config.train.pipeline_microbatches = 4
        config.train.distill_bulk = False
        config.registry.run_root = str(tmp_path / "runs")
        config.registry.root = str(tmp_path / "registry")
        return config

    run_layout_training(make_config(2), register=False, run_name="resume-me")
    ckpt_dir = tmp_path / "runs" / "resume-me" / "checkpoints"
    assert json.loads((ckpt_dir / "latest.json").read_text())["step"] == 2

    # Resume with a larger budget: continues 3..4, not 1..4.
    result = run_layout_training(
        make_config(4), register=False, run_name="resume-me"
    )
    assert json.loads((ckpt_dir / "latest.json").read_text())["step"] == 4
    # metrics.jsonl appends across the preemption: run 1's final eval
    # (step 2) plus the resumed run's (step 4) — and NO re-trained steps
    # 1..2 records, which a fresh restart would have written again.
    lines = [
        json.loads(line)
        for line in (tmp_path / "runs" / "resume-me" / "metrics.jsonl")
        .read_text()
        .splitlines()
    ]
    assert [rec["step"] for rec in lines] == [2, 4]
    assert result.bundle_dir is not None

    # Re-invoking the finished run trains zero steps and still packages.
    again = run_layout_training(
        make_config(4), register=False, run_name="resume-me"
    )
    assert again.bundle_dir is not None
    assert "validation_roc_auc_score" in again.train_result.metrics


def test_run_layout_training_pp_with_ema_packages_and_resumes(tmp_path):
    """ema_decay>0 on the PP product path: the EMA accumulator trains,
    checkpoints, RESUMES (the ema tree rides the layout checkpoint), and
    the packaged bundle carries the debiased average — which must differ
    from the raw last-step params."""
    import json

    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.config import Config, ModelConfig
    from mlops_tpu.train.pipeline import run_layout_training
    from mlops_tpu.train.pipeline_parallel import merge_bert_params

    def make_config(steps, decay):
        config = Config()
        config.data.rows = 1500
        config.model = ModelConfig(
            family="bert", token_dim=16, depth=4, heads=2, dropout=0.0,
            precision="f32", pipeline_stages=4,
        )
        config.train.batch_size = 16
        config.train.steps = steps
        config.train.eval_every = 100
        config.train.warmup_steps = 2
        config.train.checkpoint_every = 2
        config.train.pipeline_microbatches = 4
        config.train.ema_decay = decay
        config.train.distill_bulk = False
        config.registry.run_root = str(tmp_path / "runs")
        config.registry.root = str(tmp_path / "registry")
        return config

    run_layout_training(make_config(2, 0.9), register=False, run_name="ema-pp")
    ckpt_dir = tmp_path / "runs" / "ema-pp" / "checkpoints"
    assert json.loads((ckpt_dir / "latest.json").read_text())["step"] == 2

    result = run_layout_training(
        make_config(4, 0.9), register=False, run_name="ema-pp"
    )
    assert json.loads((ckpt_dir / "latest.json").read_text())["step"] == 4
    bundle = load_bundle(result.bundle_dir)

    # An identically-seeded run WITHOUT ema ships different (raw) params.
    raw = run_layout_training(
        make_config(4, 0.0), register=False, run_name="raw-pp"
    )
    raw_bundle = load_bundle(raw.bundle_dir)
    diffs = [
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(
            jax.tree.leaves(bundle.variables), jax.tree.leaves(raw_bundle.variables)
        )
    ]
    assert max(diffs) > 1e-7, diffs


def test_run_layout_training_doc_trains_and_deploys(tmp_path):
    """`train` on a doc_records+seq_parallel config runs the ring trainer
    end-to-end AND deploys (VERDICT r4 #4): the run registers a models:/
    URI, the 'doc' bundle flavor reloads, and the loaded artifact scores
    record histories — one calibrated probability per document."""
    import jax.numpy as jnp

    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.config import Config, ModelConfig
    from mlops_tpu.train.long_context import group_documents
    from mlops_tpu.train.pipeline import run_layout_training

    config = Config()
    config.data.rows = 1200
    config.model = ModelConfig(
        family="bert", token_dim=16, depth=1, heads=2, dropout=0.0,
        precision="f32", doc_records=3, seq_parallel=True,
    )
    config.train.batch_size = 8
    config.train.steps = 4
    config.train.eval_every = 2
    config.registry.run_root = str(tmp_path / "runs")
    config.registry.root = str(tmp_path / "registry")
    result = run_layout_training(config)

    assert result.model_uri and result.bundle_dir is not None
    assert (result.run_dir / "doc_params.msgpack").exists()
    assert (result.run_dir / "metrics.jsonl").exists()
    assert "validation_roc_auc_score" in result.train_result.metrics

    bundle = load_bundle(result.bundle_dir)
    assert bundle.flavor == "doc"
    assert bundle.model_config.doc_records == 3
    rng = np.random.default_rng(0)
    from mlops_tpu.schema import SCHEMA

    rows = 7  # 2 full documents + 1 dropped tail row
    cat = rng.integers(0, 2, (rows, SCHEMA.num_categorical)).astype(np.int32)
    num = rng.normal(size=(rows, SCHEMA.num_numeric)).astype(np.float32)
    dcat, dnum = group_documents(cat, num, 3)
    assert dcat.shape == (2, 3, SCHEMA.num_categorical)
    logits = bundle.model.apply(
        {"params": bundle.variables["params"]},
        jnp.asarray(dcat), jnp.asarray(dnum), train=False,
    )
    probs = jax.nn.sigmoid(jnp.asarray(logits) / bundle.temperature)
    assert probs.shape == (2,)
    assert np.isfinite(np.asarray(probs)).all()

    # The single-record serving engine refuses the flavor loudly.
    from mlops_tpu.serve.engine import InferenceEngine

    with pytest.raises(ValueError, match="predict-file"):
        InferenceEngine(bundle)


def test_predict_file_scores_doc_bundle(tmp_path, capsys):
    """The offline deployment surface: `predict-file` on a doc bundle
    groups a record-history CSV into documents and prints one calibrated
    probability per document (plus the grouping audit fields)."""
    import json

    from mlops_tpu.cli import main
    from mlops_tpu.config import Config, ModelConfig
    from mlops_tpu.data import generate_synthetic, write_csv_columns
    from mlops_tpu.train.pipeline import run_layout_training

    config = Config()
    config.data.rows = 900
    config.model = ModelConfig(
        family="bert", token_dim=16, depth=1, heads=2, dropout=0.0,
        precision="f32", doc_records=3,  # dense doc trainer (no ring)
    )
    config.train.batch_size = 8
    config.train.steps = 2
    config.train.eval_every = 2
    config.registry.run_root = str(tmp_path / "runs")
    config.registry.root = str(tmp_path / "registry")
    result = run_layout_training(config, register=False)

    csv_path = tmp_path / "history.csv"
    columns, labels = generate_synthetic(8, seed=3)  # 2 docs + 2 tail rows
    write_csv_columns(csv_path, columns, labels)
    rc = main([
        "predict-file",
        f"data.train_path={csv_path}",
        f"serve.model_directory={result.bundle_dir}",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["documents"] == 2
    assert out["records_per_document"] == 3
    assert out["rows_dropped"] == 2
    assert len(out["predictions"]) == 2
    assert all(0.0 <= p <= 1.0 for p in out["predictions"])


def test_journal_max_step_survives_truncated_line(tmp_path):
    """A preemption can truncate metrics.jsonl mid-write; the journal
    floor must still come from the intact lines, not collapse to 0 (which
    would re-append duplicate rows on resume)."""
    from mlops_tpu.train.pipeline import _journal_max_step

    path = tmp_path / "metrics.jsonl"
    path.write_text(
        '{"step": 2, "loss": 0.5}\n'
        '{"step": 4, "loss": 0.4}\n'
        '{"step": 6, "los'  # truncated by the kill
    )
    assert _journal_max_step(path) == 4
    assert _journal_max_step(tmp_path / "absent.jsonl") == 0


def test_run_training_rejects_multidevice_layout_knobs():
    """The dense entrypoint must fail LOUDLY on layout knobs it does not
    implement — a shipped pipeline/long-context config routed through
    `train` must not silently train a plain dense model."""
    from mlops_tpu.config import Config
    from mlops_tpu.train.pipeline import run_training

    from mlops_tpu.train.pipeline import run_layout_training, run_tuning

    for knob, value in (
        ("pipeline_stages", 4),
        ("seq_parallel", True),
        ("doc_records", 11),
    ):
        config = Config()
        setattr(config.model, knob, value)
        with pytest.raises(ValueError, match="dedicated trainers"):
            run_training(config, register=False)
        # The sweep trains dense models too — same loud rejection.
        with pytest.raises(ValueError, match="layout knobs"):
            run_tuning(config, register=False)
    # And the mirror: a dense config must not silently route to the
    # layout trainer (doc_records=1 would train 1-record "documents").
    with pytest.raises(ValueError, match="layout knob"):
        run_layout_training(Config(), register=False)
