"""Pipeline parallelism (parallel/pipeline.py): GPipe microbatch streaming
over a 'stage' mesh axis, validated exactly against the sequential fold."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlops_tpu.parallel.mesh import make_nd_mesh
from mlops_tpu.parallel.pipeline import make_pipeline


def _stage_fn(w, h):
    return jax.nn.gelu(h @ w[0] + w[1])


def _setup(stages, micro, batch=8, dim=16, seed=0):
    rng = np.random.default_rng(seed)
    weights = (
        jnp.asarray(rng.normal(scale=0.1, size=(stages, dim, dim)).astype(np.float32)),
        jnp.asarray(rng.normal(scale=0.1, size=(stages, dim)).astype(np.float32)),
    )
    x = jnp.asarray(rng.normal(size=(micro, batch, dim)).astype(np.float32))
    return weights, x


def _sequential(weights, x):
    out = x
    for s in range(weights[0].shape[0]):
        out = _stage_fn((weights[0][s], weights[1][s]), out)
    return out


@pytest.mark.parametrize("stages,micro", [(2, 4), (4, 8), (8, 8), (4, 1)])
def test_pipeline_matches_sequential_fold(stages, micro):
    mesh = make_nd_mesh({"stage": stages})
    weights, x = _setup(stages, micro)
    run = make_pipeline(mesh, _stage_fn)
    got = run(weights, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_sequential(weights, x)), atol=1e-5
    )


def test_pipeline_is_differentiable():
    """The scan + ppermute pipeline must transpose for training: gradients
    through the full pipeline equal gradients through the sequential fold."""
    mesh = make_nd_mesh({"stage": 4})
    weights, x = _setup(4, 4)
    run = make_pipeline(mesh, _stage_fn)

    g_pipe = jax.grad(lambda w: jnp.sum(run(w, x) ** 2))(weights)
    g_ref = jax.grad(lambda w: jnp.sum(_sequential(w, x) ** 2))(weights)
    np.testing.assert_allclose(
        np.asarray(g_pipe[0]), np.asarray(g_ref[0]), atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(g_pipe[1]), np.asarray(g_ref[1]), atol=1e-4
    )


def test_pipeline_composes_with_data_parallel_axis():
    """('data', 'stage') hybrid mesh: the pipeline must ignore extra mesh
    axes (inputs stay replicated — make_pipeline's in_specs are P() — so
    this covers axis coexistence, not a DP-sharded batch)."""
    mesh = make_nd_mesh({"data": 2, "stage": 4})
    weights, x = _setup(4, 4, batch=8)
    run = make_pipeline(mesh, _stage_fn)
    got = run(weights, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_sequential(weights, x)), atol=1e-5
    )
