"""PR 4 hot-path contract: packed single-buffer responses are BIT-IDENTICAL
to the seed dict-path responses (every bucket, every group slot shape), the
device monitor accumulator counts exactly what was scored, overlapped
batcher fetches never cross-wire requests, and the bench emits the new
breakdown/monitor keys.
"""

import asyncio
import concurrent.futures

import jax
import numpy as np
import pytest

from mlops_tpu.ops.predict import (
    make_grouped_predict_fn,
    make_padded_predict_fn,
    packed_layout,
)
from mlops_tpu.schema import SCHEMA, records_to_columns
from mlops_tpu.serve.batcher import MicroBatcher
from mlops_tpu.serve.engine import (
    GROUP_ROW_BUCKET,
    GROUP_ROW_BUCKETS,
    GROUP_SLOT_BUCKETS,
)


@pytest.fixture(scope="module")
def engine(warm_engine):
    return warm_engine  # session-shared warmed engine (conftest)


@pytest.fixture(scope="module")
def seed_padded(warm_engine):
    """The SEED dict-output padded program, bound over the same bundle —
    the pre-packing reference the parity pins against."""
    b = warm_engine.bundle
    return make_padded_predict_fn(b.model, b.variables, b.monitor, b.temperature)


@pytest.fixture(scope="module")
def seed_grouped(warm_engine):
    b = warm_engine.bundle
    return make_grouped_predict_fn(b.model, b.variables, b.monitor, b.temperature)


def _records(sample_request, k, offset=0):
    out = []
    for i in range(k):
        rec = dict(sample_request[0])
        rec["age"] = 20.0 + offset + 2.0 * i
        rec["bill_amount_1"] = 100.0 * (i + 1) + offset
        rec["credit_limit"] = 1000.0 + 977.0 * i + offset
        rec["payment_amount_1"] = 37.0 * i + offset
        out.append(rec)
    return out


def _seed_response_arrays(seed_fn, cat, num, bucket):
    """The seed engine's exact predict_arrays assembly (pad to bucket,
    device_get the dict tree, slice, cast, round)."""
    n = cat.shape[0]
    pad = bucket - n
    if pad:
        cat = np.pad(cat, ((0, pad), (0, 0)))
        num = np.pad(num, ((0, pad), (0, 0)))
    mask = np.arange(bucket) < n
    out = jax.device_get(seed_fn(cat, num, mask))
    return {
        "predictions": np.asarray(out["predictions"])[:n].astype(float).tolist(),
        "outliers": np.asarray(out["outliers"])[:n].astype(float).tolist(),
        "feature_drift_batch": dict(
            zip(
                SCHEMA.feature_names,
                np.asarray(out["feature_drift_batch"])
                .astype(float)
                .round(6)
                .tolist(),
            )
        ),
    }


# ------------------------------------------------------------ padded parity
def test_packed_padded_bit_identical_every_bucket(
    engine, seed_padded, sample_request
):
    """For EVERY warmed bucket: the packed-path response equals the seed
    dict-path response bit for bit (no tolerance)."""
    for bucket in engine.buckets:
        n = max(1, bucket - 1) if bucket > 1 else 1
        records = _records(sample_request, n, offset=bucket)
        ds = engine.bundle.preprocessor.encode(records_to_columns(records))
        got = engine.predict_arrays(ds.cat_ids, ds.numeric)
        want = _seed_response_arrays(seed_padded, ds.cat_ids, ds.numeric, bucket)
        assert got == want, f"bucket {bucket} diverged"


def test_packed_layout_slices():
    p, o, d = packed_layout(8)
    D = SCHEMA.num_categorical + SCHEMA.num_numeric
    assert (p.start, p.stop) == (0, 8)
    assert (o.start, o.stop) == (8, 16)
    assert (d.start, d.stop) == (16, 16 + D)


# ----------------------------------------------------------- grouped parity
def test_packed_grouped_bit_identical_every_slot(
    engine, seed_grouped, sample_request
):
    """For EVERY slot bucket and BOTH row families: grouped packed
    responses equal the seed grouped dict-path assembly bit for bit."""
    names = SCHEMA.feature_names

    def seed_group(requests):
        # The seed engine's exact predict_group body against the dict fn.
        import bisect

        sizes = [len(r) for r in requests]
        slots = GROUP_SLOT_BUCKETS[
            bisect.bisect_left(GROUP_SLOT_BUCKETS, len(requests))
        ]
        rows = GROUP_ROW_BUCKETS[0] if max(sizes) == 1 else GROUP_ROW_BUCKET
        cat = np.zeros((slots, rows, SCHEMA.num_categorical), np.int32)
        num = np.zeros((slots, rows, SCHEMA.num_numeric), np.float32)
        mask = np.zeros((slots, rows), bool)
        flat = [record for records in requests for record in records]
        ds = engine.bundle.preprocessor.encode(records_to_columns(flat))
        offset = 0
        for i, k in enumerate(sizes):
            cat[i, :k] = ds.cat_ids[offset : offset + k]
            num[i, :k] = ds.numeric[offset : offset + k]
            mask[i, :k] = True
            offset += k
        out = jax.device_get(seed_grouped(cat, num, mask))
        preds = np.asarray(out["predictions"]).astype(float)
        outs = np.asarray(out["outliers"]).astype(float)
        drifts = np.asarray(out["feature_drift_batch"]).astype(float).round(6)
        return [
            {
                "predictions": preds[i, :k].tolist(),
                "outliers": outs[i, :k].tolist(),
                "feature_drift_batch": dict(zip(names, drifts[i].tolist())),
            }
            for i, k in enumerate(sizes)
        ]

    for slots in GROUP_SLOT_BUCKETS:
        # Batch-1 family ([slots, 1]) at exactly this slot bucket.
        reqs = [[r] for r in _records(sample_request, slots, offset=slots)]
        assert engine.predict_group(reqs) == seed_group(reqs), (
            f"slots={slots} rows=1 diverged"
        )
        # Mixed-size family ([slots, GROUP_ROW_BUCKET]).
        mixed = [
            [r] * ((i % GROUP_ROW_BUCKET) + 1)
            for i, r in enumerate(
                _records(sample_request, slots, offset=100 + slots)
            )
        ]
        if max(len(m) for m in mixed) == 1:
            mixed[0] = mixed[0] * 2  # force the 8-row family
        assert engine.predict_group(mixed) == seed_group(mixed), (
            f"slots={slots} rows={GROUP_ROW_BUCKET} diverged"
        )


# ------------------------------------------------------ monitor accumulator
def test_monitor_accumulator_counts_scored_rows(engine, sample_request):
    assert engine.monitor_accumulating
    before = engine.monitor_snapshot()
    records = _records(sample_request, 5)
    engine.predict_records(records)  # one padded dispatch, 5 valid rows
    engine.predict_group([[r] for r in _records(sample_request, 3)])
    after = engine.monitor_snapshot()
    assert after["rows"] - before["rows"] == 8.0
    # 5-row solo = 1 batch; 3 batch-1 group slots = 3 batches.
    assert after["batches"] - before["batches"] == 4.0
    assert after["outliers"] >= before["outliers"]
    assert set(after["drift_last"]) == set(SCHEMA.feature_names)
    assert set(after["drift_mean"]) == set(SCHEMA.feature_names)


def test_monitor_accumulator_ignores_empty_requests(engine):
    before = engine.monitor_snapshot()
    out = engine.predict_arrays(
        np.zeros((0, SCHEMA.num_categorical), np.int32),
        np.zeros((0, SCHEMA.num_numeric), np.float32),
    )
    after = engine.monitor_snapshot()
    assert out["predictions"] == []
    assert after["rows"] == before["rows"]
    assert after["batches"] == before["batches"]


def test_monitor_snapshot_resets_window_keeps_exact_totals(
    engine, sample_request
):
    """Every snapshot fetches-and-RESETS the device window, folding it
    into host f64 totals: an unreset f32 counter would silently stop
    incrementing at 2^24 rows (~2 h of benched traffic). Totals must
    survive an empty window unchanged — including drift_last."""
    engine.predict_records(_records(sample_request, 3))
    first = engine.monitor_snapshot()
    window = jax.device_get(engine._acc)
    assert float(window.rows) == 0.0
    assert float(window.batches) == 0.0
    second = engine.monitor_snapshot()  # empty window
    assert second["rows"] == first["rows"]
    assert second["batches"] == first["batches"]
    assert second["drift_last"] == first["drift_last"]
    assert second["drift_mean"] == first["drift_mean"]


def test_failed_snapshot_fetch_delays_counts_not_drops_them(
    engine, sample_request, monkeypatch
):
    """A transient device_get failure in monitor_snapshot (remote-chip
    tunnel error) must fold the already-swapped-out window BACK into the
    live accumulator: the counts arrive on the next successful fetch
    instead of silently vanishing from the /metrics totals."""
    engine.monitor_snapshot()  # drain any prior window
    baseline = engine.monitor_snapshot()
    engine.predict_records(_records(sample_request, 4))

    real_get = jax.device_get

    def failing_get(x):
        raise RuntimeError("tunnel hiccup")

    monkeypatch.setattr(jax, "device_get", failing_get)
    with pytest.raises(RuntimeError, match="tunnel hiccup"):
        engine.monitor_snapshot()
    monkeypatch.setattr(jax, "device_get", real_get)

    after = engine.monitor_snapshot()  # window survived the failed fetch
    assert after["rows"] - baseline["rows"] == 4.0
    assert after["batches"] - baseline["batches"] == 1.0


def test_padding_slots_never_poison_drift_gauges(engine, sample_request):
    """A grouped dispatch with PADDING slots (3 requests -> 4-slot
    bucket): the padding slot computes drift over zero rows, where the
    chi-squared path yields NaN — the fold must select it away, not
    multiply by zero (NaN * 0 is NaN and would poison drift_sum/drift_last
    in /metrics forever)."""
    engine.monitor_snapshot()  # drain any prior window
    engine.predict_group([[r] for r in _records(sample_request, 3)])
    window = jax.device_get(engine._acc)
    assert not np.isnan(np.asarray(window.drift_sum)).any()
    assert not np.isnan(np.asarray(window.drift_last)).any()
    snap = engine.monitor_snapshot()
    assert not any(np.isnan(v) for v in snap["drift_mean"].values())
    assert not any(np.isnan(v) for v in snap["drift_last"].values())


def test_novel_shape_compiles_once_outside_warmup(engine, sample_request):
    """An oversized request (no bucket) AOT-compiles into the dispatch
    table on first sight — outside the accumulator lock — and every
    repeat reuses the entry instead of recompiling."""
    n = engine.max_bucket + 3
    records = _records(sample_request, n, offset=11)
    key = ("bucket", n)
    engine._exec.pop(key, None)
    first = engine.predict_records(records)
    assert key in engine._exec
    fn = engine._exec[key]
    second = engine.predict_records(records)
    assert engine._exec[key] is fn
    assert first == second


def test_monitor_drift_last_matches_response(engine, sample_request):
    """After a solo dispatch, the aggregate's drift_last IS that batch's
    response drift (same round(6) discipline)."""
    records = _records(sample_request, 4, offset=7)
    response = engine.predict_records(records)
    snap = engine.monitor_snapshot()
    assert snap["drift_last"] == response["feature_drift_batch"]


# ----------------------------------------------------- batcher burst safety
def test_batcher_burst_never_cross_wires_responses(engine, sample_request):
    """A burst of DISTINCT concurrent requests through the overlapped
    dispatch/fetch ring: every response must carry its own request's
    prediction — no reordering, no cross-wired futures. Distinctness is
    asserted first so a swap cannot hide."""
    requests = [[r] for r in _records(sample_request, 40)]
    expected = [engine.predict_records(r) for r in requests]
    preds = [e["predictions"][0] for e in expected]
    # Sanity floor: most fixtures must map to distinct predictions, or a
    # swap could hide (f32 sigmoid collisions cost a few duplicates; the
    # elementwise comparison below is the actual cross-wiring check).
    assert len(set(preds)) >= (len(preds) * 3) // 4, "fixture degenerate"

    async def run():
        executor = concurrent.futures.ThreadPoolExecutor(max_workers=8)
        batcher = MicroBatcher(
            engine, executor, window_ms=2.0, max_group=8, max_inflight=3
        )
        try:
            return await asyncio.gather(
                *[batcher.predict(r) for r in requests]
            )
        finally:
            executor.shutdown(wait=True)

    got = asyncio.run(run())
    assert [g["predictions"] for g in got] == [
        e["predictions"] for e in expected
    ]
    assert [g["outliers"] for g in got] == [e["outliers"] for e in expected]


def test_batcher_two_phase_fetch_releases_dispatch_slot(engine, sample_request):
    """With max_inflight=1, a second group must still be DISPATCHABLE while
    the first group's fetch is blocked — the dispatch slot is released at
    fetch time (the fetch ring owns the blocking wait)."""
    import threading
    import time

    release = threading.Event()
    real_fetch = engine.fetch_group
    fetch_started = threading.Event()

    def slow_fetch(handle):
        fetch_started.set()
        release.wait(timeout=10)
        return real_fetch(handle)

    dispatches = []
    real_dispatch = engine.dispatch_group

    def counting_dispatch(requests):
        dispatches.append(time.monotonic())
        return real_dispatch(requests)

    async def run():
        executor = concurrent.futures.ThreadPoolExecutor(max_workers=4)
        batcher = MicroBatcher(
            engine, executor, window_ms=50.0, max_group=2, max_inflight=1
        )
        batcher.engine = _Proxy(engine, counting_dispatch, slow_fetch)
        # Suppress the idle fast path: these must ride GROUPED dispatches
        # (a full group of 2 closes the window early, so the big window
        # costs nothing).
        batcher._last_enqueue = asyncio.get_running_loop().time()
        first = [
            asyncio.create_task(batcher.predict([r]))
            for r in _records(sample_request, 2)
        ]
        await asyncio.get_running_loop().run_in_executor(
            None, fetch_started.wait, 10
        )
        # First group is parked in its (stalled) fetch. A second group must
        # still dispatch under max_inflight=1.
        second = [
            asyncio.create_task(batcher.predict([r]))
            for r in _records(sample_request, 2, offset=50)
        ]
        for _ in range(200):
            if len(dispatches) >= 2:
                break
            await asyncio.sleep(0.01)
        assert len(dispatches) >= 2, "second group never dispatched"
        release.set()
        out = await asyncio.gather(*first, *second)
        executor.shutdown(wait=True)
        return out

    responses = asyncio.run(run())
    assert len(responses) == 4
    for r in responses:
        assert 0.0 <= r["predictions"][0] <= 1.0


class _Proxy:
    """Engine wrapper overriding dispatch/fetch without mutating the
    session-shared engine."""

    def __init__(self, engine, dispatch, fetch):
        self._engine = engine
        self.dispatch_group = dispatch
        self.fetch_group = fetch

    def __getattr__(self, name):
        return getattr(self._engine, name)


# ------------------------------------------------------------- bench keys
def test_bench_breakdown_and_monitor_keys(engine, sample_request):
    """The CI contract for the new bench keys: breakdown_ms carries
    fetch/fetch_copy/fetch_sync (fetch = copy + sync), the batch-1 stage
    emits lock_wait_ms (instrumented lock contention, PR 5), and the
    monitor stage emits monitor_fetch_per_s — asserted against the real
    stage functions, tier-1 (no subprocess bench run)."""
    import bench

    batch1 = bench._batch1_stage(engine, sample_request[0])
    bd = batch1["breakdown_ms"]
    assert {"encode", "dispatch", "fetch", "fetch_copy", "fetch_sync"} <= set(bd)
    # Instrumented lock wait: finite, non-negative, and small on this
    # uncontended single-caller loop (seconds would mean a lock held
    # across blocking work leaked back into the hot path).
    assert 0.0 <= batch1["lock_wait_ms"] < 1000.0
    # fetch is the median of per-rep (copy + sync) while the sub-keys are
    # per-stage medians — the two statistics drift apart whenever copy and
    # sync jitter is correlated across reps, by tens of µs under load. The
    # tolerance only needs to catch a STRUCTURAL break (a sub-stage
    # dropped from the sum ≈ ms-scale), not scheduler noise.
    assert bd["fetch"] == pytest.approx(
        bd["fetch_copy"] + bd["fetch_sync"], abs=0.2
    )
    monitor = bench._monitor_stage(engine)
    assert monitor["monitor_fetch_per_s"] > 0
    # Robustness keys (ISSUE 9): armed-off overhead ~0 (generous noise
    # bound — the pin is the KEY and its order of magnitude, not the
    # scheduler), and the degraded path measurably served requests
    # through the next warmed bucket, with the engine restored after.
    faults_stats = bench._faults_stage(engine, sample_request[0])
    assert -50.0 < faults_stats["fault_overhead_pct"] < 50.0
    assert faults_stats["degraded_p99_ms"] > 0
    assert faults_stats["degraded_dispatch_total"] == 50
    from mlops_tpu import faults as faults_mod

    assert not faults_mod.armed()  # the stage disarms on every path
    assert ("bucket", 8) in engine._exec  # the popped entry was restored
    # tracewire keys (ISSUE 10): armed-vs-disarmed overhead is a real
    # percentage (generous noise bound, same discipline as the faults
    # key), and the skewed synthetic trace produces a nonzero padding
    # waste with a positive goodput rate. The stage must disarm the
    # engine's shape stats on every path.
    trace_stats = bench._trace_stage(engine, sample_request[0])
    assert -50.0 < trace_stats["trace_overhead_pct"] < 50.0
    assert 0.0 < trace_stats["padding_waste_pct"] < 100.0
    assert trace_stats["useful_rows_per_s"] > 0
    assert engine.shape_stats is None  # disarmed after the stage
