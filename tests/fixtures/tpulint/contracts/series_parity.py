"""TPU502 fixture: series parity + label boundedness. Analyzed, never run.

Two renderer roots play the single-process and shm-ring metrics planes;
the sibling ``series_parity.yml`` plays the committed alert rules (its
typo'd series reference is planted there).
"""

TPULINT_SERIES_PLANES = {
    "single": ("SingleServer.metrics_endpoint",),
    "ring": ("RingServer.metrics_endpoint",),
}

TPULINT_PLANE_ONLY_SERIES = {
    "ring": ("mlops_tpu_fix_ring_depth",),
}

TPULINT_BOUNDED_LABELS = ("tenant",)


def shared_lines(tenant, source):
    return [
        "# TYPE mlops_tpu_fix_requests_total counter",
        f'mlops_tpu_fix_requests_total{{tenant="{tenant}"}} 1',
        f'mlops_tpu_fix_errors_total{{source="{source}"}} 0',  # PLANT: TPU502
    ]


class SingleServer:
    def metrics_endpoint(self, tenant):
        lines = shared_lines(tenant, "http")
        lines.append("mlops_tpu_fix_rows_scored_total 0")  # PLANT: TPU502
        return "\n".join(lines)


class RingServer:
    def metrics_endpoint(self, tenant):
        lines = shared_lines(tenant, "ring")
        lines.append("mlops_tpu_fix_ring_depth 0")  # allowlisted ring-only
        return "\n".join(lines)
