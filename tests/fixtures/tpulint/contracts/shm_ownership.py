"""TPU501 fixture: shm ownership violations. Analyzed, never imported.

A miniature of the serve plane's ring protocol: the manifests below play
the role of serve/ipc.py's, and every marked line writes a ring cell from
the wrong side of the process boundary.
"""

TPULINT_SHM_OWNERSHIP = {
    "sub_head": "frontend-worker",
    "shed": "frontend-worker",
    "comp_head": "engine-replica",
    # Declared handoff: the engine publishes, the supervisor resets.
    "eng_vals": ("engine-replica", "supervisor"),
}

TPULINT_SHM_ROLES = {
    "Frontend": "frontend-worker",
    "Engine": "engine-replica",
    "Engine._telemetry": "telemetry-loop",
    "respawn_supervisor": "supervisor",
}


class Frontend:
    def __init__(self, ring):
        self.ring = ring
        self.sub_head = ring.sub_head  # view construction, not a write

    def submit(self, idx):
        self.ring.sub_head[0] = idx  # owner writes its own head
        self.ring.shed[0] += 1  # owner bumps its own counter

    def steal_completion(self, idx):
        self.ring.comp_head[0] = idx  # PLANT: TPU501


class Engine:
    def __init__(self, ring):
        self.ring = ring

    def publish(self, idx):
        self.ring.comp_head[0] = idx  # owner writes its own head
        self.ring.eng_vals[0] = 1.0  # handoff tuple includes engine

    def wrong_side(self, n):
        self.ring.shed[0] += n  # PLANT: TPU501

    def _telemetry(self):
        self.ring.eng_vals[1] = 2.0  # PLANT: TPU501

    def scratch(self, x):
        self.ring.scratch_vals[0] = x  # PLANT: TPU501


class Stranger:
    """No role entry at all — even writes to correctly-named fields gate."""

    def __init__(self, ring):
        self.ring = ring

    def poke(self):
        self.ring.sub_head[0] = 7  # PLANT: TPU501


def respawn_supervisor(ring):
    ring.eng_vals[0] = 0.0  # handoff tuple includes the supervisor
