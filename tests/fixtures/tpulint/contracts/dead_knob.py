"""TPU503 fixture: a validated-but-never-read config knob.

``validate()`` reading a field does NOT make it live — that is exactly
the PR 13 ``replica_affinity_slack`` failure mode this rule exists for.
"""

import dataclasses

TPULINT_CONFIG_MODULE = True


@dataclasses.dataclass
class TunerConfig:
    max_batch: int = 64
    drain_grace_s: float = 2.0  # PLANT: TPU503

    def validate(self):
        if self.drain_grace_s < 0:
            raise ValueError("drain_grace_s must be >= 0")
        return self


def apply(config):
    return [0] * config.max_batch
