"""TPU504 fixture: fault-point liveness.

A miniature of faults/__init__.py: the module-level ``POINTS`` dict IS
the manifest, and ``fire`` sites must agree with it in both directions.
"""

POINTS = {
    "fixture.encode.bitflip": "flip one embedding id before scoring",
    "fixture.fetch.stall": "inject a device-fetch stall",  # PLANT: TPU504
}


def fire(name):
    """Stand-in for faults.fire: matched by leaf name."""
    return name


def degraded_path(batch):
    fire("fixture.encode.bitflip")
    fire("fixture.ghost.point")  # PLANT: TPU504
    return batch
