"""TPU103 fixture: data-dependent Python branches on traced values."""

import jax
import jax.numpy as jnp


@jax.jit
def branchy(x, threshold):
    if x.sum() > threshold:  # PLANT: TPU103
        return x * 2
    while threshold > 0:  # PLANT: TPU103
        threshold = threshold - 1
    return x


@jax.jit
def shape_branch_is_fine(x):
    # Static metadata branches never flag: shapes/dtypes are trace-time
    # constants.
    if x.shape[0] > 4:
        return x[:4]
    if x.ndim == 2 and len(x) > 1:
        return x.sum(axis=0)
    return x


def py_branch_is_fine(x, flag):
    if flag:
        return x
    return None
