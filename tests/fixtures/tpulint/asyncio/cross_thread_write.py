"""TPU603 fixture: a polling thread mutating state the event loop also
mutates, with no call_soon_threadsafe marshal and no shared mutex — a
data race against every coroutine touching the same attribute."""

import threading


class Plane:
    def __init__(self, loop):
        self._loop = loop
        self._lock = threading.Lock()
        self._depth = 0
        self._stats = {}
        self._seen = 0
        self._watcher = threading.Thread(target=self._poll, daemon=True)
        self._watcher.start()

    async def on_request(self):
        # Loop-confined writers: these attrs belong to the loop.
        self._depth += 1
        self._stats["requests"] = self._stats.get("requests", 0) + 1
        with self._lock:
            self._seen += 1

    def _poll(self):
        while True:
            self._depth = 0  # PLANT: TPU603
            self._stats["polls"] = 1  # PLANT: TPU603
            with self._lock:
                self._seen = 0  # both sides hold _lock: fine
            self._loop.call_soon_threadsafe(self._reset)

    def _reset(self):
        # Marshalled onto the loop via call_soon_threadsafe: this body
        # IS loop-confined, so its writes are the safe shape.
        self._depth = 0
