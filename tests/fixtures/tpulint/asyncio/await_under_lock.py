"""TPU604 fixture: awaiting while a synchronous threading mutex is held
— the loop runs arbitrary callbacks at the suspension point while every
thread queued on the lock stalls behind a coroutine that may not resume
for a long time."""

import asyncio
import threading


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._alock = asyncio.Lock()
        self._value = None

    async def refresh(self):
        with self._lock:
            self._value = await self._fetch()  # PLANT: TPU604

    async def refresh_manual(self):
        self._lock.acquire()
        result = await self._fetch()  # PLANT: TPU604
        self._lock.release()
        return result

    # ---------------------------------------------------- clean shapes
    async def refresh_async_lock(self):
        # Coroutine lock: the loop keeps running while waiters queue.
        async with self._alock:
            self._value = await self._fetch()

    async def refresh_split(self):
        # The fix shape: await first, publish under the lock.
        value = await self._fetch()
        with self._lock:
            self._value = value

    async def _fetch(self):
        await asyncio.sleep(0)
        return 42
