"""TPU602 fixture: create_task/ensure_future results that are neither
awaited, stored durably, nor observed — the "Task was destroyed but it
is pending" class, whose exceptions vanish silently."""

import asyncio


class Pump:
    def __init__(self):
        self._pump_task = None
        self._tasks = set()

    async def start_bare(self):
        asyncio.create_task(self._drain())  # PLANT: TPU602

    async def start_local(self):
        handle = asyncio.ensure_future(self._drain())  # PLANT: TPU602
        return None

    async def start_orphan_attr(self):
        self._orphan = asyncio.create_task(self._drain())  # PLANT: TPU602

    # ---------------------------------------------------- clean shapes
    async def start_awaited(self):
        task = asyncio.create_task(self._drain())
        await task

    async def start_stored(self):
        # Stored on self AND read back by stop(): observed.
        self._pump_task = asyncio.create_task(self._drain())

    async def start_collected(self):
        task = asyncio.create_task(self._drain())
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def stop(self):
        if self._pump_task is not None:
            self._pump_task.cancel()

    async def _drain(self):
        while True:
            await asyncio.sleep(0.1)
