"""TPU601 fixture: blocking calls inside event-loop-confined contexts —
the blocking-monitor-fetch-wedging-/metrics class of bug. Covers the
shared Layer-3 table, the loop-only extras, confinement propagation into
a sync helper, and the hot-mutex sub-rule (a lock Layer 3 saw held
across blocking work must not be acquired on the loop)."""

import asyncio
import subprocess
import threading
import time

import numpy as np


class Handler:
    def __init__(self):
        self._stats_lock = threading.Lock()
        self._rows = []
        self._snapshot = None
        self._count = 0
        self._event = asyncio.Event()

    # Thread-side: blocks under _stats_lock, so Layer 3 flags TPU403
    # here — which makes _stats_lock HOT for the loop-side sub-rule.
    def flush_stats(self):
        with self._stats_lock:
            self._snapshot = np.asarray(self._rows)

    async def fetch(self, handle):
        out = np.asarray(handle.out)  # PLANT: TPU601
        handle.block_until_ready()  # PLANT: TPU601
        return out

    async def backoff(self):
        time.sleep(0.1)  # PLANT: TPU601

    async def shell_out(self, cmd):
        subprocess.run(cmd)  # PLANT: TPU601
        proc = subprocess.Popen(cmd)  # PLANT: TPU601
        proc.communicate()  # PLANT: TPU601

    async def stats_endpoint(self):
        with self._stats_lock:  # PLANT: TPU601
            self._count += 1

    async def stats_probe(self):
        self._stats_lock.acquire()  # PLANT: TPU601
        try:
            return self._count
        finally:
            self._stats_lock.release()

    async def respond(self, rows):
        return self._encode(rows)

    def _encode(self, rows):
        # Reachable only from the async respond(): inherits confinement.
        return np.asarray(rows)  # PLANT: TPU601

    # ---------------------------------------------------- clean shapes
    async def fetch_offloaded(self, loop, handle):
        # The sanctioned recipe: the blocking work rides the executor.
        return await loop.run_in_executor(None, self._materialize, handle)

    def _materialize(self, handle):
        return np.asarray(handle.out)  # thread-side: fine

    async def wait_ready(self):
        # Awaited subtree: wait() here builds a coroutine, it never
        # blocks the loop.
        await asyncio.wait_for(self._event.wait(), 1.0)

    def _on_done(self, fut):
        # Registered via add_done_callback: the future is complete, so
        # result() cannot wait.
        return fut.result()

    async def submit(self, coro):
        task = asyncio.create_task(coro)
        task.add_done_callback(self._on_done)
        return await task
