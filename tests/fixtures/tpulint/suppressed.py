"""Suppression fixture: every violation here carries a disable comment,
so the analyzer must report NOTHING for this file."""

import jax
import numpy as np


@jax.jit
def justified(x):
    host = np.asarray(x)  # tpulint: disable=TPU101
    # tpulint: disable=TPU101
    also = float(x)
    return host.sum() + also


def tolerant(fn):
    try:
        return fn()
    except Exception:  # tpulint: disable
        return None


def stateful(value, into=[]):  # tpulint: disable=TPU202,TPU101
    return [*into, value]
