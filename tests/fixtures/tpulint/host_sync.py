"""TPU101 fixture: host syncs inside traced scopes. Never imported —
tests/test_analysis.py feeds this file's SOURCE to the analyzer; lines
carrying a violation are marked with a `PLANT:` comment."""

import jax
import numpy as np


@jax.jit
def decorated(x):
    y = x.sum().item()  # PLANT: TPU101
    host = np.asarray(x)  # PLANT: TPU101
    fetched = jax.device_get(x)  # PLANT: TPU101
    scalar = float(x)  # PLANT: TPU101
    return y + host.sum() + fetched + scalar


def make_step(config):
    def step(state, batch):
        listed = state.tolist()  # PLANT: TPU101
        return state + batch, listed

    return jax.jit(step, donate_argnums=0)
