"""TPU202 fixture: mutable default arguments."""


def accumulate(value, into=[]):  # PLANT: TPU202
    into.append(value)
    return into


def tag(record, labels={}):  # PLANT: TPU202
    return {**record, **labels}


def build(rows, *, cache=dict()):  # PLANT: TPU202
    return cache.setdefault("rows", rows)


def fine(value, into=None, count=0, name="x"):
    return [value] if into is None else [*into, value]
