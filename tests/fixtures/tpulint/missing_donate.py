"""TPU105 fixture: train-step jits that do not donate their state."""

import jax


def train_step(state, batch):
    return state + batch


undonated = jax.jit(train_step)  # PLANT: TPU105
donated = jax.jit(train_step, donate_argnums=0)


@jax.jit
def update_step(state, grads):  # PLANT: TPU105
    return state - grads


def predict(params, x):
    # Not a step shape: no state, no step-ish name -> never flags.
    return params @ x


served = jax.jit(predict)
