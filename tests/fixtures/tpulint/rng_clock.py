"""TPU102 fixture: Python RNG/clock calls under trace."""

import random
import time

import jax
import numpy as np


@jax.jit
def noisy(x):
    jitter = random.random()  # PLANT: TPU102
    noise = np.random.normal(size=3)  # PLANT: TPU102
    stamp = time.time()  # PLANT: TPU102
    return x + jitter + noise.sum() + stamp


def outside(x):
    # NOT traced: host-side randomness is fine here.
    return x + random.random()
