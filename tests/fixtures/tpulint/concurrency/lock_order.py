"""TPU401 fixture: lock-order inversions, cycles, undeclared nesting.

Analyzed, never imported (tests/test_analysis.py). Each violation line
carries a PLANT marker comment; the contract is exact — every planted
line fires, nothing else does.
"""

import threading

TPULINT_LOCK_ORDER = {
    "Ordered": ("_a", "_b"),
    "PartiallyDeclared": ("_a",),
}


class Ordered:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        # Declared order (_a outermost): clean.
        with self._a:
            with self._b:
                pass

    def inverted(self):
        with self._b:
            with self._a:  # PLANT: TPU401
                pass


class Cyclic:
    """No declared order: only genuine cycles are flagged."""

    def __init__(self):
        self._x = threading.Lock()
        self._y = threading.Lock()

    def xy(self):
        with self._x:
            with self._y:  # PLANT: TPU401
                pass

    def yx(self):
        with self._y:
            with self._x:  # PLANT: TPU401
                pass


class Acyclic:
    """No declared order, consistent nesting everywhere: clean."""

    def __init__(self):
        self._outer = threading.Lock()
        self._inner = threading.Lock()

    def one(self):
        with self._outer:
            with self._inner:
                pass

    def two(self):
        with self._outer:
            with self._inner:
                pass


class PartiallyDeclared:
    """A declared scope must declare EVERY lock that participates in
    nesting — a new lock slipped under an old one is flagged."""

    def __init__(self):
        self._a = threading.Lock()
        self._c = threading.Lock()

    def nested(self):
        with self._a:
            with self._c:  # PLANT: TPU401
                pass
