"""TPU403 fixture: blocking calls while a mutex is held — the
`_compile_novel`-under-`_acc_lock` class of bug (PR 4)."""

import threading
import time

import numpy as np


class Fetcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._out = None
        self._exe = None

    def fetch_under_lock(self, handle):
        with self._lock:
            self._out = np.asarray(handle.out)  # PLANT: TPU403

    def compile_under_lock(self, jitted, args):
        with self._lock:
            self._exe = jitted.lower(*args).compile()  # PLANT: TPU403

    def sync_under_lock(self, result):
        with self._lock:
            result.block_until_ready()  # PLANT: TPU403

    def sleep_under_lock(self):
        with self._lock:
            time.sleep(0.1)  # PLANT: TPU403

    def enqueue_under_lock(self, out_queue, item):
        with self._lock:
            out_queue.put(item)  # PLANT: TPU403

    def open_in_same_with_header(self, path):
        # Multi-item with: open() runs with the lock ALREADY held — same
        # hazard as the nested form, one line instead of two.
        with self._lock, open(path) as fh:  # PLANT: TPU403
            return fh.read()

    def fetch_outside_lock(self, handle):
        # The fix shape: block first, publish under the lock.
        out = np.asarray(handle.out)
        with self._lock:
            self._out = out
