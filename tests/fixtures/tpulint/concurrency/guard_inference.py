"""TPU402 fixture: attributes written both under and outside their
dominant (inferred) lock. ``__init__`` writes never count — construction
precedes sharing."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._rates = {}

    def add(self, n):
        with self._lock:
            self._count += n

    def reset_unsafe(self):
        self._count = 0  # PLANT: TPU402

    def set_rate(self, key, value):
        with self._lock:
            self._rates[key] = value

    def clear_unsafe(self):
        self._rates = {}  # PLANT: TPU402


class Consistent:
    """Every non-init write holds the guard: clean."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state = None
        self._unshared = 0  # never lock-guarded anywhere: untracked

    def swap(self, new):
        with self._lock:
            old = self._state
            self._state = new
        return old

    def bump(self):
        self._unshared += 1
