"""TPU404 fixture: semaphore acquire/release pairing across two-phase
dispatch/fetch paths."""

import threading

TPULINT_CROSS_METHOD_SEMAPHORES = {"DeclaredTwoPhase": ("_ring",)}


class LeakyRing:
    """Acquired, never released anywhere: every dispatch leaks a permit
    and the ring wedges at capacity."""

    def __init__(self):
        self._slots = threading.BoundedSemaphore(4)

    def dispatch(self, fn):
        self._slots.acquire()  # PLANT: TPU404
        return fn()


class UndeclaredTwoPhase:
    """The release exists — in another method — but nothing declares the
    cross-method pairing, so nothing would catch the fetch path dropping
    its release in a refactor."""

    def __init__(self):
        self._ring = threading.BoundedSemaphore(2)

    def dispatch(self):
        self._ring.acquire()  # PLANT: TPU404

    def fetch(self):
        self._ring.release()


class DeclaredTwoPhase:
    """Same shape, declared (TPULINT_CROSS_METHOD_SEMAPHORES): clean."""

    def __init__(self):
        self._ring = threading.BoundedSemaphore(2)

    def dispatch(self):
        self._ring.acquire()

    def fetch(self):
        self._ring.release()


class BalancedInline:
    """Acquire and release on the same function's paths: clean."""

    def __init__(self):
        self._slots = threading.BoundedSemaphore(4)

    def run(self, fn):
        self._slots.acquire()
        try:
            return fn()
        finally:
            self._slots.release()
