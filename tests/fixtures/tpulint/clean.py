"""False-positive guard fixture: TPU-idiomatic code the analyzer must pass
clean — every pattern here appears in the real codebase."""

import jax
import jax.numpy as jnp
import numpy as np


def make_padded_predict(model, variables):
    @jax.jit
    def monitors(x, mask):
        return jnp.where(mask, x, 0.0).sum()

    def predict(cat, num, mask):
        # Host predict around a jitted core: np work here is FINE — only
        # `monitors` above is traced, and scope-aware collection must not
        # confuse the two even though closures share names module-wide.
        valid = np.asarray(mask)
        return float(monitors(num, valid))

    return predict


def make_window(model, optimizer, config):
    def run_window(state, cat, num, lab):
        n = cat.shape[0]  # static metadata under trace

        def one_step(state, _):
            if config.ema_decay:  # closure config: static at trace time
                pass
            idx = jax.random.randint(state[1], (4,), 0, n)
            return state, idx.sum()

        return jax.lax.scan(one_step, state, None, length=8)

    return jax.jit(run_window, donate_argnums=0)


def host_pipeline(path, rows=None):
    # Untraced host code: syncs, clocks, branches all fine.
    import time

    start = time.time()
    data = np.asarray(range(10))
    if data.sum() > 3:
        data = data * 2
    return data, time.time() - start
