"""TPU104 fixture: jit over config-like arguments without static_argnames."""

from functools import partial

import jax


@jax.jit
def forward(config, x):  # PLANT: TPU104
    return x * config["scale"]


def loss(params, config: dict, batch):
    return params * config["weight"] * batch


bad = jax.jit(loss)  # PLANT: TPU104
good = jax.jit(loss, static_argnames=("config",))


@partial(jax.jit, static_argnames=("settings",))
def also_good(settings, x):
    return x + settings.bias
