"""Planted TPU405 violations: serving-path broad excepts that swallow the
failure without a trace. ANALYZED, never imported (tests/test_analysis.py).

The TPU201 disables are part of the plant: TPU405 is orthogonal — a
justified breadth disable does not excuse a handler that records
nothing, which is exactly what these handlers do.
"""

import logging

logger = logging.getLogger("fixture")

COUNTS = {"drops": 0}


def risky() -> None:
    raise RuntimeError("boom")


def swallowed_pass():
    try:
        risky()
    except Exception:  # tpulint: disable=TPU201  # PLANT: TPU405
        pass


def swallowed_info_log():
    try:
        risky()
    # logger.info is not an action: deployments silence INFO, so the
    # serving failure still vanishes.
    except Exception:  # tpulint: disable=TPU201  # PLANT: TPU405
        logger.info("oops")


def swallowed_plain_assign():
    try:
        risky()
    except Exception:  # tpulint: disable=TPU201  # PLANT: TPU405
        last = "failed"  # noqa: F841 — a local nobody reads is no record


# ---- compliant handlers (no findings beyond the plants above) ----------
def acts_reraise():
    try:
        risky()
    except Exception:
        raise


def acts_logs_exception():
    try:
        risky()
    except Exception:  # tpulint: disable=TPU201
        logger.exception("recorded")


def acts_returns_wire_error():
    try:
        risky()
    except Exception:  # tpulint: disable=TPU201
        return 500, {"detail": "failed"}, "application/json"


def acts_counts_metric():
    try:
        risky()
    except Exception:  # tpulint: disable=TPU201
        COUNTS["drops"] += 1


def acts_routes_to_waiter(future):
    try:
        risky()
    except Exception as err:  # tpulint: disable=TPU201
        future.set_exception(err)
