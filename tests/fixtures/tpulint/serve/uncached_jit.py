"""TPU203 fixture: jit sites under a serve/ (or parallel/) path that are
not routed through the compile-cache entry-point registry. Never imported —
analyzed only (tests/test_analysis.py). The directory name is the point:
TPU203 keys on the serve/ path segment."""

import jax


@jax.jit
def predict_probs(x):  # PLANT: TPU203
    return x * 2.0


def build_scorer(scale):
    def score(x):
        return x * scale

    return jax.jit(score)  # PLANT: TPU203


def make_chunk_scorer(scale):
    # Whitelisted builder name (compilecache/registry.py
    # CACHED_JIT_BUILDERS): its jit sites are wired through
    # cache.load_or_compile, so no finding here.
    def score(x):
        return x + scale

    return jax.jit(score)


def build_suppressed(scale):
    def score(x):
        return x - scale

    return jax.jit(score)  # tpulint: disable=TPU203
