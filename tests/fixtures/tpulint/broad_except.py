"""TPU201 fixture: broad excepts that swallow device errors."""


def swallow(fn):
    try:
        return fn()
    except Exception:  # PLANT: TPU201
        return None


def bare(fn):
    try:
        return fn()
    except:  # noqa: E722  # PLANT: TPU201
        return None


def tuple_form_is_still_broad(fn):
    try:
        return fn()
    except (ValueError, Exception):  # PLANT: TPU201
        return None


def rethrown_is_fine(fn):
    try:
        return fn()
    except Exception:
        raise


def conditional_reraise_is_fine(fn):
    try:
        return fn()
    except Exception as err:
        if "capability" not in str(err):
            raise
        return None


def narrow_is_fine(fn):
    try:
        return fn()
    except (ValueError, KeyError):
        return None
