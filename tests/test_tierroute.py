"""Per-request SLO tier routing (ISSUE 19, serve/tierroute.py): class
resolution, the brownout governor's hysteresis, the engine's class->tier
ladder, and the bit-identity contract for demoted traffic (a demoted
response must be EXACTLY what serving the cheaper tier directly returns
— demotion changes which program answers, never what that program
says)."""

import numpy as np
import pytest

from mlops_tpu.config import ServeConfig
from mlops_tpu.serve.httpcore import HttpProtocol
from mlops_tpu.serve.tierroute import (
    SLO_ACCURATE,
    SLO_CHEAP,
    SLO_DEFAULT,
    TIERS,
    BrownoutGovernor,
    parse_slo_class,
    resolve_slo_class,
    tier_for_class,
)


# ------------------------------------------------------ class resolution
def test_parse_slo_class_closed_set():
    assert parse_slo_class("default") == SLO_DEFAULT
    assert parse_slo_class("cheap") == SLO_CHEAP
    assert parse_slo_class("ACCURATE ") == SLO_ACCURATE
    assert parse_slo_class("fast") is None
    assert parse_slo_class("") is None


def test_resolve_explicit_header_wins_over_deadline():
    # A generous deadline with an explicit cheap header still routes
    # cheap; a tight deadline with an explicit accurate header is pinned.
    assert resolve_slo_class("cheap", 5000.0, 50.0) == SLO_CHEAP
    assert resolve_slo_class("accurate", 10.0, 50.0) == SLO_ACCURATE


def test_resolve_tight_deadline_routes_cheap():
    assert resolve_slo_class("", 20.0, 50.0) == SLO_CHEAP
    assert resolve_slo_class("", 50.0, 50.0) == SLO_CHEAP  # inclusive
    assert resolve_slo_class("", 51.0, 50.0) == SLO_DEFAULT
    assert resolve_slo_class("", None, 50.0) == SLO_DEFAULT
    # cheap_deadline_ms <= 0 disables deadline routing entirely
    assert resolve_slo_class("", 1.0, 0.0) == SLO_DEFAULT


def test_resolve_malformed_header_falls_through_to_deadline():
    assert resolve_slo_class("turbo", 20.0, 50.0) == SLO_CHEAP
    assert resolve_slo_class("turbo", None, 50.0) == SLO_DEFAULT


def test_tier_for_class_ladder_semantics():
    ladder = ("quant", "exact")
    assert tier_for_class(ladder, "exact", SLO_CHEAP) == "quant"
    assert tier_for_class(ladder, "exact", SLO_ACCURATE) == "exact"
    assert tier_for_class(ladder, "exact", SLO_DEFAULT) == "exact"
    assert tier_for_class(ladder, "quant", SLO_DEFAULT) == "quant"
    # one-tier engine: every class collapses onto the only program
    assert tier_for_class(("gbm",), "gbm", SLO_CHEAP) == "gbm"
    assert tier_for_class(("gbm",), "gbm", SLO_ACCURATE) == "gbm"


# ----------------------------------------------- admission header parsing
def _protocol(**cfg_kwargs) -> HttpProtocol:
    return HttpProtocol(ServeConfig(**cfg_kwargs))


def test_request_slo_disarmed_by_default():
    proto = _protocol()
    assert not proto.slo_routing
    assert proto._request_slo({"x-slo-class": "cheap"}) == SLO_DEFAULT


def test_request_slo_header_and_deadline_routing():
    proto = _protocol(tier_routing=True, slo_cheap_deadline_ms=50.0)
    assert proto.slo_routing
    assert proto._request_slo({}) == SLO_DEFAULT
    assert proto._request_slo({"x-slo-class": "cheap"}) == SLO_CHEAP
    assert proto._request_slo({"x-slo-class": "accurate"}) == SLO_ACCURATE
    assert proto._request_slo({"x-slo-class": "warp9"}) == SLO_DEFAULT
    # deadline-budget routing: tight budgets choose the cheap tier
    assert (
        proto._request_slo({"x-request-deadline-ms": "20"}) == SLO_CHEAP
    )
    assert (
        proto._request_slo({"x-request-deadline-ms": "500"}) == SLO_DEFAULT
    )
    # malformed deadline hints are ignored, never 4xx material
    assert (
        proto._request_slo({"x-request-deadline-ms": "-5"}) == SLO_DEFAULT
    )
    assert (
        proto._request_slo({"x-request-deadline-ms": "soon"}) == SLO_DEFAULT
    )


# -------------------------------------------------------------- governor
def test_brownout_governor_hysteresis_and_flap_counters():
    gov = BrownoutGovernor(demote_depth=0.75, restore_depth=0.5)
    assert not gov.observe(0.5)
    assert not gov.observe(0.74)
    assert gov.observe(0.75)  # enters at the demote threshold
    assert gov.entered == 1
    # stays active anywhere above the restore threshold (no flapping)
    assert gov.observe(0.6)
    assert gov.observe(0.51)
    assert not gov.observe(0.5)  # restores at the restore threshold
    assert gov.exited == 1
    assert not gov.observe(0.74)  # needs a fresh crossing to re-enter
    assert gov.observe(0.9)
    assert gov.entered == 2


def test_brownout_routes_default_only():
    gov = BrownoutGovernor()
    # inactive: every class passes through untouched
    assert gov.route(SLO_DEFAULT) == (SLO_DEFAULT, False)
    gov.observe(1.0)
    assert gov.route(SLO_DEFAULT) == (SLO_CHEAP, True)
    # cheap is already at the floor; accurate is the pinned escape hatch
    assert gov.route(SLO_CHEAP) == (SLO_CHEAP, False)
    assert gov.route(SLO_ACCURATE) == (SLO_ACCURATE, False)
    assert gov.demotions == 1
    assert gov.brownout_demotions == 1


def test_governor_rejects_inverted_thresholds():
    with pytest.raises(ValueError):
        BrownoutGovernor(demote_depth=0.5, restore_depth=0.5)
    with pytest.raises(ValueError):
        BrownoutGovernor(demote_depth=0.0)


def test_serve_config_validates_brownout_depths():
    from mlops_tpu.config import ServeConfigError

    cfg = ServeConfig(
        brownout_demote_depth=0.4, brownout_restore_depth=0.6
    )
    with pytest.raises(ServeConfigError, match="brownout"):
        cfg.validate()


# ------------------------------------------- multi-tier engine contract
@pytest.fixture(scope="module")
def quant_pipeline(tmp_path_factory):
    """A flax training run with the quant student opted in — the bundle
    that gates TWO serving tiers (quant + exact)."""
    from mlops_tpu.config import Config, ModelConfig, TrainConfig
    from mlops_tpu.train.pipeline import run_training

    root = tmp_path_factory.mktemp("tierroute")
    config = Config()
    config.data.rows = 3000
    config.model = ModelConfig(
        family="mlp", hidden_dims=(32, 32), embed_dim=4
    )
    config.train = TrainConfig(
        steps=100, eval_every=100, batch_size=256, distill_quant=True
    )
    config.registry.root = str(root / "registry")
    config.registry.run_root = str(root / "runs")
    result = run_training(config)
    return config, result


@pytest.fixture(scope="module")
def quant_bundle(quant_pipeline):
    from mlops_tpu.bundle import load_bundle

    _, result = quant_pipeline
    return load_bundle(result.bundle_dir)


@pytest.fixture(scope="module")
def routed_engine(quant_bundle):
    """Exact-default engine with the whole gated ladder committed."""
    from mlops_tpu.serve.engine import InferenceEngine

    assert quant_bundle.has_quant and quant_bundle.quant_gates_passed
    return InferenceEngine(
        quant_bundle, buckets=(1, 8), tier_routing=True
    )


def test_multi_tier_ladder_and_routing(routed_engine):
    assert routed_engine.default_tier == "exact"
    assert routed_engine.available_tiers == ("quant", "exact")
    for tier in routed_engine.available_tiers:
        assert tier in TIERS
    # default/accurate classes keep the default program (None = the
    # plain un-suffixed exec keys, bit-for-bit the historical dispatch)
    assert routed_engine.route_tier(SLO_DEFAULT) is None
    assert routed_engine.route_tier(SLO_ACCURATE) is None
    # cheap routes the gated student
    assert routed_engine.route_tier(SLO_CHEAP) == "quant"


def test_quant_default_engine_keeps_exact_escape_hatch(quant_bundle):
    from mlops_tpu.serve.engine import InferenceEngine

    engine = InferenceEngine(
        quant_bundle, buckets=(1,), serve_tier="quant", tier_routing=True
    )
    assert engine.default_tier == "quant"
    assert engine.available_tiers == ("quant", "exact")
    assert engine.route_tier(SLO_CHEAP) is None
    assert engine.route_tier(SLO_ACCURATE) == "exact"


def test_demoted_response_bit_identical_to_cheap_tier(
    quant_bundle, routed_engine
):
    """A brownout-demoted request (exact-default engine, tier='quant')
    returns byte-for-byte what an engine CONFIGURED for the quant tier
    serves — demotion swaps programs, never bits."""
    from mlops_tpu.serve.engine import InferenceEngine

    records = [
        {"age": 30.0, "credit_limit": 2000.0},
        {"age": 61.0, "bill_amount_1": 700.0},
    ]
    quant_native = InferenceEngine(
        quant_bundle, buckets=(1, 8), serve_tier="quant"
    )
    demoted = routed_engine.predict_records(records, tier="quant")
    native = quant_native.predict_records(records)
    assert demoted["predictions"] == native["predictions"]
    assert demoted["outliers"] == native["outliers"]
    assert (
        demoted["feature_drift_batch"] == native["feature_drift_batch"]
    )
    # ...and the default-tier path stays bit-identical to a plain
    # single-tier engine (routing must not perturb un-routed traffic).
    exact_native = InferenceEngine(quant_bundle, buckets=(1, 8))
    assert (
        routed_engine.predict_records(records)["predictions"]
        == exact_native.predict_records(records)["predictions"]
    )


def test_grouped_demotion_bit_identical(quant_bundle, routed_engine):
    from mlops_tpu.serve.engine import InferenceEngine

    requests = [
        [{"age": 25.0}],
        [{"age": 44.0, "credit_limit": 5000.0}, {"age": 31.0}],
    ]
    quant_native = InferenceEngine(
        quant_bundle, buckets=(1, 8), serve_tier="quant"
    )
    demoted = routed_engine.predict_group(requests, tier="quant")
    native = quant_native.predict_group(requests)
    for d, n in zip(demoted, native):
        assert d["predictions"] == n["predictions"]
        assert d["outliers"] == n["outliers"]


# ----------------------------------------------------- bench key contract
@pytest.mark.slow
def test_bench_tierroute_stage_key_contract(quant_bundle):
    """The CI contract for the ISSUE 19 bench keys: per-class routed
    throughput, the tier_routed_req_per_s headline, and the
    brownout-vs-shed A/B keys — asserted against the real stage function
    over a gated quant bundle."""
    import bench
    from mlops_tpu.schema import LoanApplicant

    out = bench._tierroute_stage(
        quant_bundle, LoanApplicant().model_dump()
    )
    assert out["tier_ladder"] == ["quant", "exact"]
    for label in ("default", "cheap", "accurate"):
        assert out[f"tier_req_per_s_{label}"] > 0, (label, out)
    assert out["tier_routed_req_per_s"] == out["tier_req_per_s_cheap"]
    for arm in ("on", "off"):
        assert out[f"brownout_{arm}_ok"] >= 0
        assert out[f"brownout_{arm}_goodput_req_per_s"] >= 0
    assert "brownout_goodput_gain_pct" in out
    assert out["brownout_demotions"] >= 0


def test_ring_replay_resolves_the_same_tier_from_shm(routed_engine):
    """The engine-side tier resolver reads the CLASS back out of the shm
    slot header — a respawned engine's replay therefore re-derives the
    identical tier (the crash-survivability half of the routing
    contract)."""

    class _Ring:
        slot_slo = np.array([SLO_CHEAP, SLO_DEFAULT], np.uint32)

    class _Svc:
        ring = _Ring()
        engines = [routed_engine]

    from mlops_tpu.serve.ipc import RingService

    assert RingService._slot_tier(_Svc(), 0, 0) == "quant"
    assert RingService._slot_tier(_Svc(), 1, 0) is None
