"""Test harness: force an 8-device CPU-simulated mesh before JAX imports.

Standard JAX fake-backend trick (SURVEY.md SS4 build obligation (d)): all
multi-chip logic is exercised without a TPU via
``--xla_force_host_platform_device_count=8``. Real-TPU benchmarks run
out-of-band through ``bench.py``.
"""

import os
import sys
from pathlib import Path

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# The environment's TPU bootstrap (sitecustomize) force-sets
# jax_platforms="axon,cpu" at interpreter start, overriding the env var and
# making any backend init dial the TPU tunnel. Override back at the config
# level BEFORE any backend is initialized so tests stay on the fake 8-device
# CPU mesh.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite compiles many identical
# programs across modules (engine warmups, train steps at shared shapes);
# caching them cuts suite wall time substantially both within a run and
# across CI runs (ci.yml caches the directory). Override the location with
# MLOPS_TPU_TEST_CACHE; it is never checked in (.gitignore).
_cache_dir = os.environ.get(
    "MLOPS_TPU_TEST_CACHE", str(Path(__file__).parent / ".jax_cache")
)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

import numpy as np
import pytest


@pytest.fixture(scope="session")
def synth_small():
    from mlops_tpu.data import generate_synthetic

    columns, labels = generate_synthetic(2000, seed=7)
    return columns, labels


@pytest.fixture(scope="session")
def encoded_small(synth_small):
    from mlops_tpu.data import Preprocessor

    columns, labels = synth_small
    prep = Preprocessor.fit(columns)
    return prep, prep.encode(columns, labels)


@pytest.fixture(scope="session")
def tiny_pipeline(tmp_path_factory):
    """One small end-to-end training run shared by bundle/serve/CLI tests."""
    from mlops_tpu.config import Config, ModelConfig, TrainConfig
    from mlops_tpu.train.pipeline import run_training

    root = tmp_path_factory.mktemp("pipeline")
    config = Config()
    config.data.rows = 3000
    config.model = ModelConfig(family="mlp", hidden_dims=(32, 32), embed_dim=4)
    config.train = TrainConfig(steps=100, eval_every=100, batch_size=256)
    config.registry.root = str(root / "registry")
    config.registry.run_root = str(root / "runs")
    result = run_training(config)
    return config, result


@pytest.fixture(scope="session")
def warm_engine(tiny_pipeline):
    """ONE fully-warmed serving engine shared by the serve/batcher modules
    (each warmup compiles 4 bucket + 6 group shapes — two identical
    engines cost ~90 s of duplicate compiles on the CI box). Tests must
    not mutate it; anything needing special buckets builds its own."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.serve.engine import InferenceEngine

    _, result = tiny_pipeline
    engine = InferenceEngine(load_bundle(result.bundle_dir), buckets=(1, 8, 64))
    engine.warmup()
    return engine


@pytest.fixture(scope="session")
def sample_request():
    """The reference's exact smoke-test payload (`app/sample-request.json`)."""
    return [
        {
            "sex": "male",
            "education": "university",
            "marriage": "married",
            "repayment_status_1": "duly_paid",
            "repayment_status_2": "duly_paid",
            "repayment_status_3": "duly_paid",
            "repayment_status_4": "duly_paid",
            "repayment_status_5": "no_delay",
            "repayment_status_6": "no_delay",
            "credit_limit": 18000,
            "age": 18000,
            "bill_amount_1": 764.95,
            "bill_amount_2": 2221.95,
            "bill_amount_3": 1131.85,
            "bill_amount_4": 5074.85,
            "bill_amount_5": 18000,
            "bill_amount_6": 1419.95,
            "payment_amount_1": 2236.5,
            "payment_amount_2": 1137.55,
            "payment_amount_3": 5084.55,
            "payment_amount_4": 111.65,
            "payment_amount_5": 306.9,
            "payment_amount_6": 805.65,
        }
    ]
