"""Parallelism tests on the fake 8-device CPU mesh (SURVEY.md SS4 (d))."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlops_tpu.config import ModelConfig, TrainConfig
from mlops_tpu.models import build_model, init_params
from mlops_tpu.parallel import (
    make_mesh,
    make_sharded_batch_scorer,
    make_sharded_train_step,
    mesh_shape_for,
    param_shardings,
)
from mlops_tpu.parallel.collectives import all_gather_rows, pmean_over_data, ring_shift
from mlops_tpu.schema import NUM_CATEGORICAL, NUM_NUMERIC
from mlops_tpu.train.loop import TrainState, make_optimizer


def test_devices_available():
    assert jax.device_count() == 8  # conftest forces the fake mesh


def test_mesh_shapes():
    assert mesh_shape_for(8, 1) == (8, 1)
    assert mesh_shape_for(8, 2) == (4, 2)
    with pytest.raises(ValueError):
        mesh_shape_for(8, 3)
    mesh = make_mesh(8, model_parallel=2)
    assert mesh.axis_names == ("data", "model")
    assert mesh.devices.shape == (4, 2)


def test_param_rules_hit_dense_kernels():
    model = build_model(ModelConfig(family="mlp", hidden_dims=(64, 64)))
    variables = init_params(model, jax.random.PRNGKey(0))
    mesh = make_mesh(8, model_parallel=2)
    shardings = param_shardings(mesh, variables["params"])
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(shardings)[0]
    }
    # Column-parallel a-kernels shard the output dim over 'model'.
    a_specs = [s.spec for name, s in flat.items() if "dense_0a/kernel" in name]
    assert a_specs and all(spec[1] == "model" for spec in a_specs)
    b_specs = [s.spec for name, s in flat.items() if "dense_0b/kernel" in name]
    assert b_specs and all(spec[0] == "model" for spec in b_specs)


def _batch(n, seed=0):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.integers(0, 2, (n, NUM_CATEGORICAL)).astype(np.int32)),
        jnp.asarray(rng.normal(size=(n, NUM_NUMERIC)).astype(np.float32)),
        jnp.asarray((rng.random(n) < 0.2).astype(np.float32)),
    )


@pytest.mark.parametrize("family,model_parallel", [("mlp", 2), ("ft_transformer", 2)])
def test_sharded_train_step_runs_and_matches_single_device(family, model_parallel):
    config = ModelConfig(
        family=family,
        hidden_dims=(64, 64),
        token_dim=32,
        depth=1,
        heads=4,
        dropout=0.0,
        precision="f32",  # exact comparison across layouts
    )
    tconfig = TrainConfig(batch_size=32, steps=1, learning_rate=1e-3)
    model = build_model(config)
    variables = init_params(model, jax.random.PRNGKey(0))
    optimizer = make_optimizer(tconfig)
    mesh = make_mesh(8, model_parallel=model_parallel)
    step_fn, shardings = make_sharded_train_step(
        model, optimizer, tconfig, mesh, variables["params"]
    )
    state = TrainState(
        params=variables["params"],
        opt_state=optimizer.init(variables["params"]),
        step=jnp.asarray(0, jnp.int32),
        rng=jax.random.PRNGKey(1),
    )
    cat, num, lab = _batch(32)

    # Single-device reference loss with identical inputs — computed BEFORE
    # the sharded step because donation invalidates the param buffers.
    from mlops_tpu.train.loop import sigmoid_bce

    def loss_of(params):
        logits = model.apply({"params": params}, cat, num, train=False)
        return sigmoid_bce(logits, lab, tconfig.pos_weight)

    ref_loss = float(loss_of(variables["params"]))

    new_state, loss = step_fn(state, cat, num, lab, jax.random.PRNGKey(2))
    assert np.isfinite(float(loss))
    assert int(new_state.step) == 1
    assert abs(float(loss) - ref_loss) < 1e-4


def test_sharded_train_step_accumulates_ema():
    """ema_decay>0 on the DP/TP step: the accumulator starts at zero,
    updates to (1-d)*params after one step, and lives on the params'
    shardings (no replicated shadow of a TP-sharded layer)."""
    from mlops_tpu.train.loop import debias_ema

    config = ModelConfig(
        family="mlp", hidden_dims=(32, 32), dropout=0.0, precision="f32"
    )
    tconfig = TrainConfig(
        batch_size=32, steps=1, learning_rate=1e-3, ema_decay=0.9
    )
    model = build_model(config)
    variables = init_params(model, jax.random.PRNGKey(0))
    optimizer = make_optimizer(tconfig)
    mesh = make_mesh(8, model_parallel=2)
    step_fn, shardings = make_sharded_train_step(
        model, optimizer, tconfig, mesh, variables["params"]
    )
    assert shardings.ema is not None
    state = TrainState(
        params=variables["params"],
        opt_state=optimizer.init(variables["params"]),
        step=jnp.asarray(0, jnp.int32),
        rng=jax.random.PRNGKey(1),
        ema=jax.tree_util.tree_map(jnp.zeros_like, variables["params"]),
    )
    cat, num, lab = _batch(32)
    new_state, _ = step_fn(state, cat, num, lab, jax.random.PRNGKey(2))
    # One step from a zero accumulator: debiased EMA == updated params.
    debiased = debias_ema(new_state.ema, tconfig.ema_decay, new_state.step)
    for e, p in zip(
        jax.tree_util.tree_leaves(debiased),
        jax.tree_util.tree_leaves(new_state.params),
    ):
        np.testing.assert_allclose(np.asarray(e), np.asarray(p), rtol=1e-5)
    # The accumulator adopted the param shardings (spec match, not device).
    for e_sh, p_sh in zip(
        jax.tree_util.tree_leaves(shardings.ema),
        jax.tree_util.tree_leaves(shardings.params),
    ):
        assert e_sh.spec == p_sh.spec


def test_sharded_batch_scorer_matches_local(tiny_pipeline):
    from mlops_tpu.bundle import load_bundle

    _, result = tiny_pipeline
    bundle = load_bundle(result.bundle_dir)
    mesh = make_mesh(8, model_parallel=1)
    scorer = make_sharded_batch_scorer(bundle.model, mesh)
    cat, num, _ = _batch(64, seed=5)
    sharded = np.asarray(scorer(bundle.variables, cat, num))
    local = np.asarray(
        jax.nn.sigmoid(bundle.model.apply(bundle.variables, cat, num, train=False))
    )
    np.testing.assert_allclose(sharded, local, rtol=2e-2, atol=2e-3)


def test_collectives_semantics():
    mesh = make_mesh(8, model_parallel=1)
    x = jnp.arange(16.0)

    mean_fn = pmean_over_data(lambda s: s.sum(), mesh)
    # Each shard holds 2 elements; pmean of shard-sums = total/8.
    assert float(mean_fn(x)) == pytest.approx(float(x.sum()) / 8)

    gathered = all_gather_rows(mesh)(x)
    np.testing.assert_array_equal(np.asarray(gathered), np.asarray(x))

    shifted = ring_shift(mesh)(x)
    expected = np.roll(np.asarray(x).reshape(8, 2), 1, axis=0).reshape(-1)
    np.testing.assert_array_equal(np.asarray(shifted), expected)


def test_param_rules_skip_ensemble_member_axis():
    """Deep-ensemble params carry a leading member axis; the TP rules must
    land on the kernel's own trailing dims and replicate the member axis."""
    model = build_model(
        ModelConfig(family="mlp", ensemble_size=4, hidden_dims=(64, 64))
    )
    variables = init_params(model, jax.random.PRNGKey(0))
    mesh = make_mesh(8, model_parallel=2)
    shardings = param_shardings(mesh, variables["params"])
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(shardings)[0]
    }
    a_specs = [s.spec for name, s in flat.items() if "dense_0a/kernel" in name]
    # kernel is [K, in, out]: member axis replicated, output dim on 'model'
    assert a_specs and all(
        spec[0] is None and spec[2] == "model" for spec in a_specs
    )
    b_specs = [s.spec for name, s in flat.items() if "dense_0b/kernel" in name]
    assert b_specs and all(
        spec[0] is None and spec[1] == "model" for spec in b_specs
    )


def test_sharded_train_step_runs_with_ensemble():
    """The DP/TP train step composes with the ensemble's member vmap."""
    config = ModelConfig(
        family="mlp", ensemble_size=2, hidden_dims=(32, 32), dropout=0.0,
        precision="f32",
    )
    tconfig = TrainConfig(batch_size=32, steps=1, learning_rate=1e-3)
    model = build_model(config)
    variables = init_params(model, jax.random.PRNGKey(0))
    optimizer = make_optimizer(tconfig)
    mesh = make_mesh(8, model_parallel=2)
    step_fn, _ = make_sharded_train_step(
        model, optimizer, tconfig, mesh, variables["params"]
    )
    state = TrainState(
        params=variables["params"],
        opt_state=optimizer.init(variables["params"]),
        step=jnp.asarray(0, jnp.int32),
        rng=jax.random.PRNGKey(1),
    )
    cat, num, lab = _batch(32)
    new_state, loss = step_fn(state, cat, num, lab, jax.random.PRNGKey(2))
    assert np.isfinite(float(loss))
    assert int(new_state.step) == 1
