"""Numeric sanitizers (SURVEY.md SS5.2 build stance)."""

import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import checkify

from mlops_tpu.schema import SCHEMA
from mlops_tpu.utils.debug import check_encoded_inputs, checked


def test_checked_passes_clean_fn():
    fn = checked(lambda x: jnp.log(x + 1.0))
    out = fn(jnp.ones(4))
    np.testing.assert_allclose(np.asarray(out), np.log(2.0), rtol=1e-6)


def test_checked_raises_on_nan():
    fn = checked(lambda x: jnp.log(x))  # log(-1) -> NaN
    with pytest.raises(checkify.JaxRuntimeError):
        fn(-jnp.ones(4))


def test_checked_predict_fn_on_bundle(tiny_pipeline):
    """The served fused predict is NaN-clean under float_checks."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.ops.predict import make_padded_predict_fn

    _, result = tiny_pipeline
    bundle = load_bundle(result.bundle_dir)
    predict = make_padded_predict_fn(
        bundle.model, bundle.variables, bundle.monitor
    )
    wrapped = checked(predict.__wrapped__, jit=True)
    cat = np.zeros((4, SCHEMA.num_categorical), np.int32)
    num = np.zeros((4, SCHEMA.num_numeric), np.float32)
    out = wrapped(cat, num, np.ones(4, bool))
    assert np.isfinite(np.asarray(out["predictions"])).all()


def test_check_encoded_inputs():
    n = 3
    cat = np.zeros((n, SCHEMA.num_categorical), np.int32)
    num = np.zeros((n, SCHEMA.num_numeric), np.float32)
    check_encoded_inputs(cat, num)  # clean

    bad_cat = cat.copy()
    bad_cat[1, 2] = 10_000
    with pytest.raises(ValueError, match="out of range"):
        check_encoded_inputs(bad_cat, num)

    bad_num = num.copy()
    bad_num[0, 0] = np.nan
    with pytest.raises(ValueError, match="non-finite"):
        check_encoded_inputs(cat, bad_num)

    with pytest.raises(ValueError, match="shape"):
        check_encoded_inputs(cat[:, :3], num)
