"""Schema contract tests — parity with the reference's field lists."""

from mlops_tpu.schema import (
    CATEGORICAL_FEATURES,
    FEATURE_NAMES,
    NUM_CATEGORICAL,
    NUM_FEATURES,
    NUM_NUMERIC,
    SCHEMA,
    FeatureBatchDrift,
    LoanApplicant,
    ModelOutput,
    records_to_columns,
)

# The reference's exact field order (`app/model.py:8-34`).
REFERENCE_FIELDS = [
    "sex",
    "education",
    "marriage",
    "repayment_status_1",
    "repayment_status_2",
    "repayment_status_3",
    "repayment_status_4",
    "repayment_status_5",
    "repayment_status_6",
    "credit_limit",
    "age",
    "bill_amount_1",
    "bill_amount_2",
    "bill_amount_3",
    "bill_amount_4",
    "bill_amount_5",
    "bill_amount_6",
    "payment_amount_1",
    "payment_amount_2",
    "payment_amount_3",
    "payment_amount_4",
    "payment_amount_5",
    "payment_amount_6",
]


def test_feature_names_match_reference_contract():
    assert list(FEATURE_NAMES) == REFERENCE_FIELDS
    assert NUM_CATEGORICAL == 9
    assert NUM_NUMERIC == 14
    assert NUM_FEATURES == 23


def test_pydantic_models_generated_from_schema():
    assert list(LoanApplicant.model_fields) == REFERENCE_FIELDS
    assert list(FeatureBatchDrift.model_fields) == REFERENCE_FIELDS
    assert set(ModelOutput.model_fields) == {
        "predictions",
        "outliers",
        "feature_drift_batch",
    }


def test_applicant_defaults_and_validation(sample_request):
    # Full sample request parses.
    parsed = [LoanApplicant(**r) for r in sample_request]
    assert parsed[0].sex == "male"
    # Empty record takes schema defaults (reference gives every field a
    # default, `app/model.py:12-34`).
    empty = LoanApplicant()
    assert empty.education == "university"
    assert empty.credit_limit == 18000.0
    # The reference's age=18000.0 default bug is deliberately not replicated.
    assert empty.age == 35.0


def test_oov_encoding():
    edu = CATEGORICAL_FEATURES[1]
    assert edu.encode("university") == 1
    assert edu.encode("never-seen-value") == edu.oov_id
    assert edu.card == len(edu.vocab) + 1


def test_records_to_columns(sample_request):
    columns = records_to_columns(sample_request)
    assert set(columns) == set(FEATURE_NAMES)
    assert columns["sex"] == ["male"]
    assert columns["payment_amount_6"] == [805.65]
    # Missing keys fall back to defaults.
    columns2 = records_to_columns([{}])
    assert columns2["marriage"] == ["married"]


def test_fingerprint_stable():
    assert SCHEMA.fingerprint() == SCHEMA.fingerprint()
    assert len(SCHEMA.fingerprint()) == 16
