"""Long-context BERT over the ('data','seq') mesh (train/long_context.py).

VERDICT r3 item 7: ring attention wired into a REAL training config, not
just its own unit tests — a ~508-token document model whose attention runs
as the ppermute ring, trained end-to-end on the fake 8-device mesh, with
dense-equivalence pinned at tolerance.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlops_tpu.config import ModelConfig, TrainConfig
from mlops_tpu.data import Preprocessor, generate_synthetic
from mlops_tpu.parallel.mesh import make_nd_mesh
from mlops_tpu.schema import SCHEMA
from mlops_tpu.train.long_context import (
    build_doc_model,
    make_doc_train_step,
    make_documents,
)

DOC_RECORDS = 11  # seq = 2 + 46*11 = 508 tokens, divisible by seq axis 4


def doc_config(**kw) -> ModelConfig:
    return ModelConfig(
        family="bert",
        doc_records=DOC_RECORDS,
        token_dim=32,
        depth=2,
        heads=4,
        precision="f32",  # equivalence tolerances are f32-tight
        **kw,
    )


@pytest.fixture(scope="module")
def documents():
    columns, labels = generate_synthetic(2200, seed=31)
    prep = Preprocessor.fit(columns)
    ds = prep.encode(columns, labels)
    return make_documents(ds, DOC_RECORDS)


def test_make_documents_shapes(documents):
    cat, num, lab = documents
    assert cat.shape == (200, DOC_RECORDS, SCHEMA.num_categorical)
    assert num.shape == (200, DOC_RECORDS, SCHEMA.num_numeric)
    assert lab.shape == (200,)
    assert set(np.unique(lab)) <= {0.0, 1.0}


def test_doc_seq_len_is_long_context():
    model = build_doc_model(doc_config())
    assert model.doc_seq_len == 508


def test_ring_forward_matches_dense(documents):
    """Same params, same inputs: the ring-sharded forward must equal the
    dense single-device forward at f32 tolerance."""
    cat, num, _ = documents
    cat, num = jnp.asarray(cat[:16]), jnp.asarray(num[:16])
    mesh = make_nd_mesh({"data": 2, "seq": 4})
    dense = build_doc_model(doc_config())
    ring = build_doc_model(doc_config(seq_parallel=True), mesh)
    params = dense.init(
        {"params": jax.random.PRNGKey(0)}, cat, num, train=False
    )["params"]
    out_dense = dense.apply({"params": params}, cat, num, train=False)
    out_ring = ring.apply({"params": params}, cat, num, train=False)
    np.testing.assert_allclose(
        np.asarray(out_dense), np.asarray(out_ring), atol=2e-4, rtol=2e-4
    )


# Heaviest end-to-end path (~60s serial on CPU): excluded from the
# timed tier-1 gate; CI's parallel pytest job still runs it.
@pytest.mark.slow
def test_sp_training_step_loss_decreases(documents):
    """The REAL config path: seq_parallel=true over {'data':2,'seq':4},
    25 train steps at seq 508 — loss must decrease."""
    cat, num, lab = documents
    mesh = make_nd_mesh({"data": 2, "seq": 4})
    trainer = make_doc_train_step(
        doc_config(seq_parallel=True),
        TrainConfig(learning_rate=3e-3, weight_decay=1e-4),
        mesh=mesh,
    )
    params, opt_state = trainer.params, trainer.opt_state
    batch = 32
    rng = np.random.default_rng(0)
    losses = []
    for i in range(25):
        idx = rng.integers(0, cat.shape[0], batch)
        params, opt_state, _, loss = trainer.step_fn(
            params, opt_state, trainer.ema,
            jnp.asarray(cat[idx]), jnp.asarray(num[idx]), jnp.asarray(lab[idx]),
        )
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses


def test_doc_trainer_accumulates_ema(documents):
    """ema_decay>0 on the document trainer: the accumulator threads
    through step_fn and the one-step debiased average equals the updated
    params (zero init ⇒ ema/(1-d) == params after step 1)."""
    from mlops_tpu.train.loop import debias_ema

    cat, num, lab = documents
    trainer = make_doc_train_step(
        doc_config(),
        TrainConfig(learning_rate=1e-3, ema_decay=0.9),
        mesh=None,
    )
    assert trainer.ema is not None
    take = 8
    params, opt_state, ema, _ = trainer.step_fn(
        trainer.params, trainer.opt_state, trainer.ema,
        jnp.asarray(cat[:take]), jnp.asarray(num[:take]),
        jnp.asarray(lab[:take]),
    )
    debiased = debias_ema(ema, 0.9, 1)
    for e, p in zip(
        jax.tree_util.tree_leaves(debiased), jax.tree_util.tree_leaves(params)
    ):
        np.testing.assert_allclose(
            np.asarray(e), np.asarray(p), rtol=1e-5, atol=1e-7
        )


def test_sp_step_matches_dense_step(documents):
    """One optimizer step, ring vs dense, SAME init: losses and updated
    param trees agree at tolerance — the ring changes layout, not math."""
    cat, num, lab = documents
    take = 16
    cat_j, num_j = jnp.asarray(cat[:take]), jnp.asarray(num[:take])
    lab_j = jnp.asarray(lab[:take])
    mesh = make_nd_mesh({"data": 2, "seq": 4})
    tconfig = TrainConfig(learning_rate=1e-3)
    dense = make_doc_train_step(doc_config(), tconfig, mesh=None, seed=3)
    ring = make_doc_train_step(
        doc_config(seq_parallel=True), tconfig, mesh=mesh, seed=3
    )
    # Identical seeds -> identical init (same module tree/names).
    p_d, o_d, _, loss_d = dense.step_fn(
        dense.params, dense.opt_state, None, cat_j, num_j, lab_j
    )
    p_r, o_r, _, loss_r = ring.step_fn(
        ring.params, ring.opt_state, None, cat_j, num_j, lab_j
    )
    np.testing.assert_allclose(float(loss_d), float(loss_r), atol=1e-4)
    flat_d = jax.tree_util.tree_leaves(p_d)
    flat_r = jax.tree_util.tree_leaves(p_r)
    for a, b in zip(flat_d, flat_r):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-3
        )


def test_seq_parallel_requires_seq_axis():
    with pytest.raises(ValueError, match="'seq' axis"):
        build_doc_model(doc_config(seq_parallel=True), mesh=None)


def test_dropout_rejected_on_ring_path(documents):
    """Attention-weight dropout cannot combine with the injected ring."""
    from mlops_tpu.models.layers import MultiHeadSelfAttention

    x = jnp.zeros((2, 8, 16))
    attn = MultiHeadSelfAttention(heads=2, dropout=0.5, attend_fn=lambda q, k, v: q)
    variables = attn.init(
        {"params": jax.random.PRNGKey(0)}, x, deterministic=True
    )
    with pytest.raises(ValueError, match="ring attention"):
        attn.apply(
            variables,
            x,
            deterministic=False,
            rngs={"dropout": jax.random.PRNGKey(1)},
        )
