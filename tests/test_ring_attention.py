"""Ring attention vs dense reference on the fake 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlops_tpu.ops.attention import reference_attention
from mlops_tpu.parallel import make_nd_mesh, make_ring_attention


def _qkv(key, b=2, s=64, h=4, d=16):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return (
        jax.random.normal(kq, shape, jnp.float32),
        jax.random.normal(kk, shape, jnp.float32),
        jax.random.normal(kv, shape, jnp.float32),
    )


def test_matches_dense_reference_seq8():
    q, k, v = _qkv(jax.random.PRNGKey(0))
    mesh = make_nd_mesh({"seq": 8})
    ring = make_ring_attention(mesh, "seq")
    np.testing.assert_allclose(
        np.asarray(ring(q, k, v)),
        np.asarray(reference_attention(q, k, v)),
        atol=1e-5,
        rtol=1e-5,
    )


def test_combined_data_and_sequence_parallel():
    q, k, v = _qkv(jax.random.PRNGKey(1), b=4, s=32)
    mesh = make_nd_mesh({"data": 2, "seq": 4})
    ring = make_ring_attention(mesh, "seq", batch_axis="data")
    np.testing.assert_allclose(
        np.asarray(ring(q, k, v)),
        np.asarray(reference_attention(q, k, v)),
        atol=1e-5,
        rtol=1e-5,
    )


def test_gradients_match_dense():
    """scan + ppermute path must be reverse-differentiable (training use)."""
    q, k, v = _qkv(jax.random.PRNGKey(2), b=1, s=16, h=2, d=8)
    mesh = make_nd_mesh({"seq": 4})
    ring = make_ring_attention(mesh, "seq")

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), atol=1e-4, rtol=1e-4
        )


def test_uneven_seq_rejected():
    q, k, v = _qkv(jax.random.PRNGKey(3), s=20)
    mesh = make_nd_mesh({"seq": 8})
    ring = make_ring_attention(mesh, "seq")
    with pytest.raises(Exception):
        ring(q, k, v)


def test_nd_mesh_too_many_devices():
    with pytest.raises(ValueError):
        make_nd_mesh({"data": 4, "seq": 4})


def test_three_way_dp_sp_tp_head_sharding():
    """DP×SP×TP: batch over 'data', sequence over 'seq', HEADS over
    'model' (Megatron-composed ring) — heads are independent in
    attention, so the 3-axis layout must reproduce dense exactly with the
    K/V ring hops confined to the 'seq' axis."""
    q, k, v = _qkv(jax.random.PRNGKey(7), b=4, s=32, h=4, d=16)
    mesh = make_nd_mesh({"data": 2, "seq": 2, "model": 2})
    ring = make_ring_attention(
        mesh, "seq", batch_axis="data", head_axis="model"
    )
    np.testing.assert_allclose(
        np.asarray(ring(q, k, v)),
        np.asarray(reference_attention(q, k, v)),
        atol=1e-5,
        rtol=1e-5,
    )


def test_head_sharded_ring_is_differentiable():
    q, k, v = _qkv(jax.random.PRNGKey(8), b=2, s=16, h=4, d=8)
    mesh = make_nd_mesh({"seq": 2, "model": 2})
    ring = make_ring_attention(mesh, "seq", head_axis="model")

    def loss_ring(q):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_dense(q):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    np.testing.assert_allclose(
        np.asarray(jax.grad(loss_ring)(q)),
        np.asarray(jax.grad(loss_dense)(q)),
        atol=1e-4,
        rtol=1e-4,
    )
