# Outputs — analogue of `infrastructure/main.bicep:188-198` (all resource
# names + the Databricks hostname; here: everything CI needs to deploy).

output "artifact_registry" {
  value = "${var.region}-docker.pkg.dev/${var.project_id}/${google_artifact_registry_repository.images.repository_id}"
}

output "data_bucket" {
  value = google_storage_bucket.data.name
}

output "gke_clusters" {
  value = { for env, c in google_container_cluster.env : env => c.name }
}

output "deploy_service_account" {
  value = google_service_account.deploy.email
}

output "workload_identity_provider" {
  value = google_iam_workload_identity_pool_provider.github.name
}
