# Parameters — analogue of `infrastructure/main.bicep:8-23` (resource group,
# location, deploy flags).

variable "project_id" {
  type        = string
  description = "GCP project to deploy into"
}

variable "region" {
  type        = string
  default     = "us-west4" # v5e availability
  description = "Region for GKE, Artifact Registry and the data bucket"
}

variable "zone" {
  type        = string
  default     = "us-west4-a"
  description = "Zone for the TPU node pools (v5e zones only)"
}

variable "github_repository" {
  type        = string
  description = "owner/repo allowed to federate onto the deploy identity"
}

# Parity with the reference's deployKubernetesService flag
# (`main.bicep:16-23`); container-apps has no GCP analogue — Cloud Run
# cannot schedule TPUs, so GKE is the single serving target.
variable "deploy_kubernetes_service" {
  type    = bool
  default = true
}

variable "tpu_topology" {
  type        = string
  default     = "1x1" # one v5e chip per serving node
  description = "TPU podslice topology for the serving node pools"
}

variable "environments" {
  type        = list(string)
  default     = ["staging", "production"] # parity: main.bicep:140-182 pairs
  description = "One GKE cluster + TPU pool per environment"
}
