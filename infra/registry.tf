# Artifact Registry — analogue of `infrastructure/modules/
# container-registry.bicep` (ACR Standard with AcrPull role to the managed
# identity; `main.bicep:117-123`). On GKE, image pull auth is the node
# service account's artifactregistry.reader binding — no admin user,
# no attach-acr step (`deploy-infrastructure.yml:252-260` has no analogue).

resource "google_artifact_registry_repository" "images" {
  repository_id = "mlops-tpu-${local.suffix}"
  location      = var.region
  format        = "DOCKER"
  labels        = local.labels
}

resource "google_service_account" "deploy" {
  account_id   = "mlops-tpu-deploy-${local.suffix}"
  display_name = "CI deploy identity (GitHub OIDC federated)"
}

resource "google_artifact_registry_repository_iam_member" "ci_push" {
  repository = google_artifact_registry_repository.images.name
  location   = var.region
  role       = "roles/artifactregistry.writer"
  member     = "serviceAccount:${google_service_account.deploy.email}"
}

resource "google_project_iam_member" "ci_gke" {
  project = var.project_id
  role    = "roles/container.developer"
  member  = "serviceAccount:${google_service_account.deploy.email}"
}

resource "google_storage_bucket_iam_member" "ci_data" {
  bucket = google_storage_bucket.data.name
  role   = "roles/storage.objectAdmin"
  member = "serviceAccount:${google_service_account.deploy.email}"
}

# GitHub OIDC federation — analogue of the reference's Azure federated
# credentials setup (`.github/docs/step-by-step-setup.md:43-120`).
resource "google_iam_workload_identity_pool" "github" {
  workload_identity_pool_id = "github-${local.suffix}"
}

resource "google_iam_workload_identity_pool_provider" "github" {
  workload_identity_pool_id          = google_iam_workload_identity_pool.github.workload_identity_pool_id
  workload_identity_pool_provider_id = "github-oidc"
  attribute_mapping = {
    "google.subject"       = "assertion.sub"
    "attribute.repository" = "assertion.repository"
  }
  # GCP requires a condition on new GitHub OIDC providers; scope the trust
  # to this repository only.
  attribute_condition = "attribute.repository == \"${var.github_repository}\""
  oidc {
    issuer_uri = "https://token.actions.githubusercontent.com"
  }
}

# The binding that makes federation actually work: GitHub workflows from
# this repo may mint tokens AS the deploy service account (the GCP
# analogue of the reference's federated-credential subject entries,
# `.github/docs/step-by-step-setup.md:43-120` there).
resource "google_service_account_iam_member" "github_federation" {
  service_account_id = google_service_account.deploy.name
  role               = "roles/iam.workloadIdentityUser"
  member             = "principalSet://iam.googleapis.com/${google_iam_workload_identity_pool.github.name}/attribute.repository/${var.github_repository}"
}
