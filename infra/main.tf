# GCP estate for the TPU-native stack — analogue of the reference's
# subscription-scope `infrastructure/main.bicep` (SURVEY.md §2.4), re-based
# from Azure (ACR/AKS/ACA/Databricks/Log Analytics) onto GCP:
#
#   ACR                     -> Artifact Registry        (registry.tf)
#   AKS staging+production  -> GKE + TPU node pools     (gke.tf)
#   Log Analytics + omsagent-> Cloud Logging/Monitoring (built into GKE)
#   Databricks workspace    -> none: training runs in-cluster on the TPU
#                              pool via the framework's own trainer
#   user-assigned identity  -> service accounts + workload identity (registry.tf)
#   storage account         -> GCS bucket for datasets + model registry
#
# Same shape as the reference: one orchestrating entry point, staging and
# production pairs behind a flag, all names exported as outputs.

terraform {
  required_version = ">= 1.5"
  required_providers {
    google = {
      source  = "hashicorp/google"
      version = "~> 6.0"
    }
    random = {
      source  = "hashicorp/random"
      version = "~> 3.6"
    }
  }
}

provider "google" {
  project = var.project_id
  region  = var.region
}

# Deterministic short suffix (parity with `main.bicep:29`'s uniqueString).
resource "random_id" "suffix" {
  byte_length = 3
}

locals {
  suffix = random_id.suffix.hex
  labels = {
    workload = "credit-default-mlops"
    stack    = "mlops-tpu"
  }
}

# Dataset + registry bucket (reference: storage-account.bicep + DBFS upload,
# `deploy-infrastructure.yml:195-198`).
resource "google_storage_bucket" "data" {
  name                        = "${var.project_id}-mlops-tpu-${local.suffix}"
  location                    = var.region
  uniform_bucket_level_access = true
  labels                      = local.labels

  versioning {
    enabled = true # model-registry bundles are immutable versions
  }
}
