# GKE clusters with TPU v5e node pools — analogue of
# `infrastructure/modules/kubernetes-service.bicep` (AKS Free tier, 2x
# Standard_B2s, omsagent->Log Analytics), rebuilt for TPU serving:
# staging/production pair selected by label (the reference selects AKS
# clusters by an `environment` tag, `deploy-kubernetes.yml:231-232`).

resource "google_container_cluster" "env" {
  for_each = var.deploy_kubernetes_service ? toset(var.environments) : []

  name     = "mlops-tpu-${each.key}-${local.suffix}"
  location = var.zone

  # Separately-managed node pools; the default pool hosts system pods and
  # the CPU side of the workload (ingress, metrics).
  remove_default_node_pool = true
  initial_node_count       = 1

  resource_labels = merge(local.labels, { environment = each.key })

  # Cloud Logging/Monitoring replace the omsagent->Log Analytics wiring
  # (`kubernetes-service.bicep:53-61`); on GKE they are first-party.
  logging_service    = "logging.googleapis.com/kubernetes"
  monitoring_service = "monitoring.googleapis.com/kubernetes"

  workload_identity_config {
    workload_pool = "${var.project_id}.svc.id.goog"
  }
}

resource "google_container_node_pool" "system" {
  for_each = google_container_cluster.env

  name       = "system"
  cluster    = each.value.name
  location   = var.zone
  node_count = 1

  node_config {
    machine_type = "e2-standard-4"
    labels       = local.labels
    oauth_scopes = ["https://www.googleapis.com/auth/cloud-platform"]
  }
}

# The TPU pool: ct5lp-hightpu-1t = one v5e chip per node; the serving
# Deployment lands here via google.com/tpu requests + the
# gke-tpu-accelerator/topology node selectors (kubernetes/manifest.yml).
resource "google_container_node_pool" "tpu" {
  for_each = google_container_cluster.env

  name     = "tpu-v5e"
  cluster  = each.value.name
  location = var.zone

  autoscaling {
    min_node_count = 1
    max_node_count = each.key == "production" ? 4 : 2
  }

  node_config {
    machine_type = "ct5lp-hightpu-1t"
    labels       = merge(local.labels, { environment = each.key })
    oauth_scopes = ["https://www.googleapis.com/auth/cloud-platform"]

    # Preemption-tolerant serving: checkpointed bundles reload in seconds
    # and the PDB keeps one replica up (staging only; prod on-demand).
    spot = each.key != "production"
  }

  # GKE injects the TPU device plugin + topology labels automatically for
  # ct5lp machine types; var.tpu_topology documents the slice shape.
}
