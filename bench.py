"""Inference benchmark — the headline number (BASELINE.md north star:
p50 < 5 ms and >= 2k req/s per chip for credit-default inference).

Runs on whatever backend JAX selects (the real TPU chip under the driver;
CPU if forced). Flow: train the flagship serving model briefly, build the
warmed engine, then measure:

- batch-1 end-to-end latency through the full serving path
  (records -> encode -> device -> classifier+drift+outlier -> host), and
- bulk throughput at the largest serving bucket.

Prints ONE JSON line:
``{"metric", "value", "unit", "vs_baseline", ...extras}`` where
``vs_baseline`` = (5 ms target) / (measured p50) — >1.0 beats the target.
"""

from __future__ import annotations

import json
import time


def _acquire_device(timeout_s: int):
    """First device, with a watchdog: probe TPU init in a SUBPROCESS (the
    tunnel dial blocks in C++ where in-process alarms can't interrupt);
    if the probe doesn't come back healthy in time, pin this process to
    CPU so the bench always emits its one JSON line instead of hanging a
    round. An explicit JAX_PLATFORMS env skips the probe."""
    import os
    import subprocess
    import sys

    import jax

    if not os.environ.get("JAX_PLATFORMS"):
        try:
            # DEVNULL, not pipes: the TPU plugin forks tunnel helpers that
            # inherit stdio; after the timeout-kill a captured pipe would
            # keep subprocess.run blocked on EOF forever.
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
            healthy = probe.returncode == 0
        except subprocess.TimeoutExpired:
            healthy = False
        if not healthy:
            print(
                f"# tpu backend not healthy within {timeout_s}s; "
                "benchmarking on cpu",
                flush=True,
            )
            try:
                jax.config.update("jax_platforms", "cpu")
            except RuntimeError:
                pass
    return jax.devices()[0]


def main() -> None:
    # Honor an explicit JAX_PLATFORMS env (the container bootstrap otherwise
    # pins the TPU backend, hanging CPU-only runs on the tunnel dial).
    import os

    from mlops_tpu.commands import _honor_jax_platforms_env

    _honor_jax_platforms_env()

    import numpy as np

    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.config import Config, ModelConfig, TrainConfig
    from mlops_tpu.serve.engine import InferenceEngine
    from mlops_tpu.train.pipeline import run_training
    from mlops_tpu.utils.timing import percentile

    device = _acquire_device(int(os.environ.get("BENCH_TPU_TIMEOUT_S", "300")))

    config = Config()
    config.data.rows = 50_000
    config.model = ModelConfig(family="mlp")
    config.train = TrainConfig(
        batch_size=1024, steps=600, eval_every=600, warmup_steps=60
    )
    config.registry.run_root = "runs/bench"
    result = run_training(config, register=False, run_name="bench")
    bundle = load_bundle(result.bundle_dir)

    # Grouping off: the bench measures sequential batch-1 latency and bulk
    # throughput; the 3 grouped-shape compiles would be dead weight.
    engine = InferenceEngine(bundle, buckets=(1, 8, 64, 256), enable_grouping=False)
    engine.warmup()

    # --- batch-1 latency through the full serving path -------------------
    from mlops_tpu.schema import LoanApplicant

    record = LoanApplicant().model_dump()
    for _ in range(20):  # post-warmup steady state
        engine.predict_records([record])
    latencies = []
    for _ in range(300):
        t0 = time.perf_counter()
        engine.predict_records([record])
        latencies.append((time.perf_counter() - t0) * 1e3)
    latencies.sort()
    p50 = percentile(latencies, 50)
    p99 = percentile(latencies, 99)

    # --- bulk throughput at the largest bucket ---------------------------
    rng = np.random.default_rng(0)
    from mlops_tpu.schema import SCHEMA

    n = 256
    cat = rng.integers(0, 2, (n, SCHEMA.num_categorical)).astype(np.int32)
    num = rng.normal(size=(n, SCHEMA.num_numeric)).astype(np.float32)
    engine.predict_arrays(cat, num)
    t0 = time.perf_counter()
    reps = 50
    for _ in range(reps):
        engine.predict_arrays(cat, num)
    dt = time.perf_counter() - t0
    rows_per_s = reps * n / dt

    print(
        json.dumps(
            {
                "metric": "inference_p50_latency_ms",
                "value": round(p50, 4),
                "unit": "ms",
                "vs_baseline": round(5.0 / p50, 3),
                "p99_ms": round(p99, 4),
                "batch1_req_per_s": round(1e3 / p50, 1),
                "bulk_rows_per_s": round(rows_per_s, 1),
                "device": str(device),
                "model_auc": round(
                    result.train_result.metrics["validation_roc_auc_score"], 4
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
