"""Inference benchmark — the headline number (BASELINE.md north star:
p50 < 5 ms and >= 2k req/s per chip for credit-default inference).

Runs on whatever backend JAX selects (the real TPU chip under the driver;
CPU if forced). Flow: train the flagship serving model briefly, build the
warmed engine, then measure:

- batch-1 end-to-end latency through the full serving path
  (records -> encode -> device -> classifier+drift+outlier -> host),
  decomposed into encode / dispatch / fetch stages,
- bulk throughput at buckets {256, 4096, 16384} plus a pipelined sweep
  (dispatch all chunks, one batched fetch) on both the exact ensemble and
  the auto-routed bulk path (distilled student on CPU backends),
- the streaming-executor sweep (data/pipeline_exec.py): a synthetic
  200k-row CSV scored serial vs pipelined through `score_csv_stream`,
  with per-stage occupancies and an output bit-identity check
  (``bulk_stream_*`` keys),
- roofline evidence: XLA-counted FLOPs ÷ wall ÷ chip peak (``mfu_*`` keys)
  for bulk inference, the fused train step, and the flash-attention
  kernel (utils/flops.py),
- cold-start evidence (compilecache/): ``engine_cold_start_s`` vs
  ``engine_warm_start_s`` — two FRESH processes warming against one AOT
  executable cache dir (first compiles + persists, second deserializes)
  with cache hit/miss counts,
- direct engine grouped-dispatch capability (no HTTP layer), and
- HTTP-level req/s through the real asyncio server + micro-batcher at
  client concurrency {1, 8, 32, 128}, on an ``http_workers`` axis:
  workers=1 is the single-process server (``http_req_per_s_c*`` /
  ``http_w1_*``), workers in {2, 4} the SO_REUSEPORT front-end plane
  over the shared-memory ring (``http_w2_*`` / ``http_w4_*``), plus the
  ``http_vs_engine_ratio`` derived key (best HTTP point over the
  engine's direct grouped req/s) and ``shed_503_pct`` from an overload
  burst at 10x the best concurrency (load-shedding evidence), and
- the lifecycle loop (mlops_tpu/lifecycle/) on a synthetic drift-injected
  trace, run LAST because the gated promotion hot-swaps the live bundle:
  ``retrain_trigger_to_promote_s``, ``swap_downtime_ms`` (p99 delta
  across a live promotion under concurrent traffic — the zero-downtime
  claim), and ``shadow_mirror_overhead_pct``.

Prints ONE JSON line no matter what:
``{"metric", "value", "unit", "vs_baseline", ...extras}`` where
``vs_baseline`` = (5 ms target) / (measured p50) — >1.0 beats the target.
A crash prints the same shape with an ``"error"`` field (exit code 1).

Env knobs: ``BENCH_MODEL`` (any model family — mlp, gbm/rf,
ft_transformer, moe, linear; default mlp), ``BENCH_ENSEMBLE``
(deep-ensemble members for the mlp flagship, default 8; 1 = single
model), ``BENCH_TPU_TIMEOUT_S`` (per-attempt TPU health-probe watchdog,
default 150) with ``BENCH_TPU_RETRIES``/``BENCH_TPU_BACKOFF_S`` retry
knobs (default 3 attempts, 30 s doubling backoff — a flapping tunnel gets
several chances before the run falls back to measured CPU numbers),
``BENCH_WALL_TIMEOUT_S`` (PER-ATTEMPT wall budget guarding against
mid-run device stalls, default 2100; a stalled TPU attempt re-execs one
CPU attempt with a fresh budget, so the worst-case total is ~2x plus
the init probe), ``JAX_PLATFORMS`` (force a backend; honored via
mlops_tpu's config re-assert before backend init).
"""

from __future__ import annotations

import json
import os
import sys
import time

_REEXEC_FLAG = "BENCH_FORCED_CPU"

# Set immediately before the success line is printed; the wall watchdog
# checks it so a timer that fires during/after the final print can never
# clobber a completed run's output (Timer.cancel alone cannot close that
# race — cancel on an already-fired timer is a no-op).
import threading as _threading

_BENCH_DONE = _threading.Event()


def _on_tpu_path() -> bool:
    """True when this run is headed for the TPU backend: JAX_PLATFORMS
    unset (site default dials the TPU) or naming a TPU platform — this
    harness exports ``JAX_PLATFORMS=axon`` AMBIENTLY, so a TPU-flavored
    value is the default path, not a user override. Only a non-TPU value
    (e.g. ``cpu``, or a bogus name in the contract tests) expresses an
    explicit choice the fallbacks must respect. Re-exec'd runs are never
    on the TPU path."""
    if os.environ.get(_REEXEC_FLAG):
        return False
    value = os.environ.get("JAX_PLATFORMS", "")
    return value == "" or "axon" in value.lower() or "tpu" in value.lower()


def _kill_children() -> None:
    """SIGKILL direct children before a mid-run re-exec: an orphaned HTTP
    load client (or probe) would survive the exec blocked on a pipe no one
    reads. Best effort — /proc scan, no psutil."""
    import signal

    me = os.getpid()
    try:
        for pid_dir in os.listdir("/proc"):
            if not pid_dir.isdigit():
                continue
            try:
                with open(f"/proc/{pid_dir}/stat") as f:
                    fields = f.read().split()
                if int(fields[3]) == me:
                    os.kill(int(pid_dir), signal.SIGKILL)
            except (OSError, ValueError, IndexError):
                continue
    except OSError:
        pass


def _reexec_on_cpu(reason: str) -> None:
    """Replace this process with a CPU-forced retry. Never returns; if the
    exec itself fails, fall back to the one-JSON-line error contract (an
    exception escaping a watchdog thread would otherwise leave the stalled
    process hanging forever — the exact failure the caller is handling)."""
    try:
        print(f"# {reason}; re-exec on cpu", flush=True)
        _kill_children()
        env = dict(os.environ, JAX_PLATFORMS="cpu", **{_REEXEC_FLAG: "1"})
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)
    except BaseException as err:
        print(_error_line(f"{reason}; cpu re-exec failed: {err}"), flush=True)
        os._exit(1)


def _probe_tpu_once(timeout_s: int) -> bool:
    """One subprocess TPU-init probe (the tunnel dial blocks in C++ where
    in-process alarms can't interrupt)."""
    import subprocess

    try:
        # DEVNULL, not pipes: the TPU plugin forks tunnel helpers that
        # inherit stdio; after the timeout-kill a captured pipe would
        # keep subprocess.run blocked on EOF forever.
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return probe.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _ensure_healthy_backend(timeout_s: int) -> None:
    """Probe TPU init with bounded RETRY + BACKOFF before giving up. A
    remote-attached chip's tunnel flaps (observed live: dead at round end,
    back minutes later — the reason BENCH_r03 recorded CPU numbers), so a
    single failed probe re-trying a few times is the difference between a
    driver-captured TPU benchmark and a software-floor one. After the last
    failed attempt, RE-EXEC this process under ``JAX_PLATFORMS=cpu`` — the
    in-process ``jax.config.update`` fallback is shadowed whenever the
    site bootstrap already initialized the backend (BENCH_r01 failure
    mode), while a fresh process + the env re-assert in
    ``_honor_jax_platforms_env`` cannot be. Only a non-TPU
    ``JAX_PLATFORMS`` (or a prior re-exec) skips the probe — the harness
    exports ``JAX_PLATFORMS=axon`` ambiently (see ``_on_tpu_path``).

    Knobs: ``BENCH_TPU_TIMEOUT_S`` per-attempt budget, ``BENCH_TPU_RETRIES``
    attempts (default 3), ``BENCH_TPU_BACKOFF_S`` first sleep between
    attempts (default 30, doubling)."""
    if not _on_tpu_path():
        return
    attempts = max(1, int(os.environ.get("BENCH_TPU_RETRIES", "3")))
    backoff = float(os.environ.get("BENCH_TPU_BACKOFF_S", "30"))
    for attempt in range(attempts):
        if _probe_tpu_once(timeout_s):
            return
        if attempt < attempts - 1:
            print(
                f"# tpu probe {attempt + 1}/{attempts} failed; "
                f"retrying in {backoff:.0f}s",
                flush=True,
            )
            time.sleep(backoff)
            backoff *= 2
    _reexec_on_cpu(
        f"tpu backend not healthy in {attempts} probe(s) of {timeout_s}s"
    )


def _percentile(sorted_ms: list[float], q: float) -> float:
    from mlops_tpu.utils.timing import percentile

    return percentile(sorted_ms, q)


def _p50_ms(fn, reps: int = 60) -> float:
    """Median wall of ``reps`` calls of ``fn`` — the armed-vs-disarmed
    overhead measurement shared by the faults and trace stages."""
    lat = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        lat.append((time.perf_counter() - t0) * 1e3)
    lat.sort()
    return _percentile(lat, 50)


_T0 = time.perf_counter()


def _note(msg: str) -> None:
    """Stage-progress breadcrumb on STDERR (stdout carries the one-JSON-line
    contract). On a flapping remote-chip tunnel the wall watchdog can fire
    mid-run; these timestamps are how a post-mortem tells 'stage X is slow'
    from 'the device died during stage X' (round-4 diagnosis need)."""
    print(f"# bench +{time.perf_counter() - _T0:7.1f}s {msg}", file=sys.stderr, flush=True)


def _batch1_stage(engine, record) -> dict:
    """p50/p99 of the full serving path + a stage breakdown.

    The breakdown walks the engine's real two-phase API (PR 4): host
    encode, async device dispatch (`dispatch_arrays` returns a handle),
    ``fetch_copy`` = starting the packed buffer's async D2H copy
    (`copy_to_host_async`), ``fetch_sync`` = the blocking remainder
    (host-copy wait + response slicing). ``fetch`` = copy + sync is kept
    for cross-round comparability with the seed's single fetch number.
    """
    from mlops_tpu.schema import records_to_columns

    for _ in range(20):  # post-warmup steady state
        engine.predict_records([record])
    lat = []
    for _ in range(150):
        t0 = time.perf_counter()
        engine.predict_records([record])
        lat.append((time.perf_counter() - t0) * 1e3)
    lat.sort()

    # Stage decomposition (medians over 50 reps).
    enc, disp, copy, sync = [], [], [], []
    for _ in range(50):
        t0 = time.perf_counter()
        columns = records_to_columns([record])
        ds = engine.bundle.preprocessor.encode(columns)
        t1 = time.perf_counter()
        handle = engine.dispatch_arrays(ds.cat_ids, ds.numeric)
        t2 = time.perf_counter()
        handle.start_copy()
        t3 = time.perf_counter()
        engine.fetch_arrays(handle)
        t4 = time.perf_counter()
        enc.append((t1 - t0) * 1e3)
        disp.append((t2 - t1) * 1e3)
        copy.append((t3 - t2) * 1e3)
        sync.append((t4 - t3) * 1e3)
    mid = len(enc) // 2
    # fetch = median of per-rep (copy + sync): the SAME statistic as the
    # seed's single measured fetch stage — a sum of the two sub-stage
    # medians would drift from it whenever copy and sync are correlated
    # across reps, making round-over-round deltas an artifact.
    fetch = sorted(c + s for c, s in zip(copy, sync))[mid]

    # Lock-contention satellite: total blocked time across the engine's
    # locks (_acc_lock, the jit-compile lock, ...) over a dedicated
    # instrumented rep loop — SEPARATE from the latency loops above so the
    # wrapper's per-acquire bookkeeping never taints p50/p99
    # comparability with earlier rounds. Near-zero when uncontended; a
    # regression that makes a request hold a lock across blocking work
    # (the PR 4 _compile_novel class, tpulint TPU403) shows here as soon
    # as anything else wants the lock.
    from mlops_tpu.analysis.lockcheck import instrument_locks

    with instrument_locks(engine) as sanitizer:
        for _ in range(50):
            engine.predict_records([record])
    return {
        "p50_ms": _percentile(lat, 50),
        "p99_ms": _percentile(lat, 99),
        "lock_wait_ms": round(sanitizer.total_wait_ms, 3),
        "breakdown_ms": {
            "encode": round(sorted(enc)[mid], 3),
            "dispatch": round(sorted(disp)[mid], 3),
            "fetch": round(fetch, 3),
            "fetch_copy": round(sorted(copy)[mid], 3),
            "fetch_sync": round(sorted(sync)[mid], 3),
        },
    }


def _monitor_stage(engine) -> dict:
    """Throughput of the device-monitor aggregate read
    (`InferenceEngine.monitor_snapshot` — the telemetry path that replaced
    the per-request host fold): snapshots/s, fetched OFF the request path
    every K requests / T seconds by the server."""
    if not getattr(engine, "monitor_accumulating", False):
        return {}
    engine.monitor_snapshot()  # warm
    reps = 50
    t0 = time.perf_counter()
    for _ in range(reps):
        engine.monitor_snapshot()
    dt = time.perf_counter() - t0
    return {"monitor_fetch_per_s": round(reps / dt, 1)}


def _faults_stage(engine, record) -> dict:
    """Robustness evidence (mlops_tpu/faults — ISSUE 9):

    - ``fault_overhead_pct``: hot-path cost of the fault-injection
      subsystem when it is NOT firing — batch-1 p50 with the module
      disarmed (the product state) vs armed with a zero-match plan (every
      ``fire()`` takes its slow path, nothing injects). Expected ~0.
    - ``degraded_p99_ms``: p99 of requests served through the DEGRADED
      dispatch path — the target bucket's compile failing (seeded fault
      at serve.engine.compile) and every request riding the next larger
      warmed bucket instead of 500ing — plus the counter delta proving
      the path actually ran. Engine state is restored afterwards.
    """
    from mlops_tpu import faults

    engine.predict_records([record])  # steady state
    disarmed = _p50_ms(lambda: engine.predict_records([record]))
    faults.arm(
        faults.FaultPlan.from_rules(
            [{"point": "bench.no.such.point", "mode": "raise"}]
        )
    )
    try:
        armed_off = _p50_ms(lambda: engine.predict_records([record]))
    finally:
        faults.disarm()
    out: dict = {
        "fault_overhead_pct": round(
            (armed_off / max(disarmed, 1e-9) - 1.0) * 100.0, 2
        )
    }
    if not getattr(engine, "monitor_accumulating", False):
        return out  # no exec table on the sklearn flavor — no degraded path

    records = [record] * 3  # target bucket 8; degrades to the next warmed
    with engine._compile_lock:
        saved = engine._exec.pop(("bucket", 8), None)
    before = engine.degraded_dispatch_total
    faults.arm(
        faults.FaultPlan.from_rules(
            [{"point": "serve.engine.compile", "mode": "raise"}]
        )
    )
    try:
        lat = []
        for _ in range(50):
            t0 = time.perf_counter()
            engine.predict_records(records)
            lat.append((time.perf_counter() - t0) * 1e3)
    finally:
        faults.disarm()
        if saved is not None:
            with engine._compile_lock:
                engine._exec[("bucket", 8)] = saved
    lat.sort()
    out["degraded_p99_ms"] = round(_percentile(lat, 99), 3)
    out["degraded_dispatch_total"] = engine.degraded_dispatch_total - before
    return out


def _trace_stage(engine, record) -> dict:
    """tracewire evidence (mlops_tpu/trace — ISSUE 10):

    - ``trace_overhead_pct``: batch-1 p50 with tracing DISARMED (the
      product default — every hook is an is-None check) vs ARMED (span
      per request + shape-stat fold + recorder enqueue). Acceptance:
      <= 2 armed, ~0 disarmed (the disarmed number IS the baseline every
      other stage measured).
    - ``padding_waste_pct`` / ``useful_rows_per_s``: the goodput keys
      from a SKEWED synthetic trace — request sizes drawn log-uniform
      across the bucket grid, so every bucket pads — computed by the
      same ShapeStats the /metrics histograms export. This is ROADMAP
      item 4's autotuner input: the waste an optimized bucket set would
      reclaim.

    Engine trace state restored afterwards (shape_stats back to None).
    """
    import tempfile

    import numpy as np

    from mlops_tpu.schema import SCHEMA
    from mlops_tpu.trace import ShapeStats, Span, TraceRecorder

    engine.predict_records([record])  # steady state
    disarmed = _p50_ms(lambda: engine.predict_records([record]))
    out: dict = {}
    with tempfile.TemporaryDirectory() as td:
        recorder = TraceRecorder(f"{td}/spans.jsonl", capacity=8192)
        engine.set_shape_stats(ShapeStats())
        try:

            def traced():
                span = Span("bench", plane="bench")
                engine.predict_records([record], span=span)
                span.stamp("respond")
                recorder.record(span.finish(200))

            armed = _p50_ms(traced)
        finally:
            engine.set_shape_stats(None)
            recorder.close()
    out["trace_overhead_pct"] = round(
        (armed / max(disarmed, 1e-9) - 1.0) * 100.0, 2
    )

    # Skewed synthetic shape trace -> goodput keys.
    rng = np.random.default_rng(7)
    sizes = np.unique(
        np.rint(np.exp(rng.uniform(0.0, np.log(200.0), 60))).astype(int)
    )
    # Stay inside the warmed bucket grid: an oversized request would
    # exact-shape-compile (a novel program per size), measuring XLA
    # compilation instead of padding waste.
    sizes = sizes[sizes <= getattr(engine, "max_bucket", sizes.max())]
    stats = ShapeStats()
    engine.set_shape_stats(stats)
    try:
        requested = 0
        t0 = time.perf_counter()
        for n in sizes:
            cat = rng.integers(0, 2, (int(n), SCHEMA.num_categorical)).astype(
                np.int32
            )
            num = rng.normal(size=(int(n), SCHEMA.num_numeric)).astype(
                np.float32
            )
            engine.predict_arrays(cat, num)
            requested += int(n)
        elapsed = time.perf_counter() - t0
    finally:
        engine.set_shape_stats(None)
    out["padding_waste_pct"] = stats.padding_waste_pct()
    out["useful_rows_per_s"] = round(requested / max(elapsed, 1e-9), 1)
    return out


def _slo_stage(engine, record) -> dict:
    """sloscope evidence (mlops_tpu/slo — ISSUE 14):

    - ``slo_overhead_pct``: batch-1 p50 with sloscope DISARMED (the
      product default — every hook is an is-None check) vs ARMED
      (flight-recorder request note + cost-ledger fold on the fetch
      path). Both loops include the pre-existing metrics fold, so the
      delta isolates exactly what arming adds. The SLO engine's tick
      itself runs on a timer OFF the request path and is excluded by
      construction. DRIFT-RESISTANT: the disarmed baseline is measured
      BEFORE AND AFTER the armed loop and the faster of the two is the
      denominator — on a box whose steady state is still settling (or
      under background load), a single before-only baseline can make
      the armed loop read faster than disarmed, which is measurement
      drift, not physics. Acceptance: ~0 disarmed, and the armed delta
      is the documented number.
    - ``slo_armed_p50_ms``: the armed batch-1 p50 (the absolute armed
      cost, so rounds compare it directly).

    Engine ledger state restored afterwards (cost_ledger back to None).
    """
    import tempfile

    from mlops_tpu.config import SLOConfig
    from mlops_tpu.serve.metrics import ServingMetrics
    from mlops_tpu.slo import CostLedger, FlightRecorder, SLOEngine

    metrics = ServingMetrics()

    def observed_predict() -> None:
        t0 = time.perf_counter()
        engine.predict_records([record])
        metrics.observe_request(
            "/predict", 200, (time.perf_counter() - t0) * 1e3
        )

    observed_predict()  # steady state
    disarmed = _p50_ms(observed_predict)
    out: dict = {}
    with tempfile.TemporaryDirectory() as td:
        cfg = SLOConfig(
            enabled=True, flightrec_dir=td, ledger_dir=td
        ).validate()
        flightrec = FlightRecorder(
            td,
            capacity=cfg.flightrec_capacity,
            cooldown_s=cfg.flightrec_cooldown_s,
            keep=cfg.flightrec_keep,
            source="bench",
        )
        ledger = CostLedger(td, flush_interval_s=3600)
        slo = SLOEngine(
            cfg,
            ("default",),
            source=lambda: metrics.slo_counts(
                cfg.latency_threshold_ms, ("default",)
            ),
        )
        engine.set_cost_ledger(ledger)
        try:

            def armed_call() -> None:
                t0 = time.perf_counter()
                engine.predict_records([record])
                ms = (time.perf_counter() - t0) * 1e3
                metrics.observe_request("/predict", 200, ms)
                flightrec.observe_request("/predict", 200, ms)

            armed = _p50_ms(armed_call)
            slo.tick()  # evaluator sanity: clean traffic fires nothing
            assert not slo.any_alert_active(), slo.view()
            assert flightrec.dumps == 0
        finally:
            engine.set_cost_ledger(None)
            ledger.close()
    disarmed = min(disarmed, _p50_ms(observed_predict))  # drift guard
    out["slo_overhead_pct"] = round(
        (armed / max(disarmed, 1e-9) - 1.0) * 100.0, 2
    )
    out["slo_armed_p50_ms"] = round(armed, 4)
    return out


def _bulk_stage(engine, bundle) -> dict:
    """rows/s at fixed buckets (sequential, one blocking call per batch)
    and pipelined (dispatch all chunks, single batched device_get)."""
    import numpy as np

    from mlops_tpu.data.encode import EncodedDataset
    from mlops_tpu.parallel.bulk import score_dataset
    from mlops_tpu.schema import SCHEMA

    rng = np.random.default_rng(0)
    out: dict[str, float] = {}
    for n, reps in ((256, 20), (4096, 10), (16384, 5)):
        _note(f"bulk bucket n={n}")
        cat = rng.integers(0, 2, (n, SCHEMA.num_categorical)).astype(np.int32)
        num = rng.normal(size=(n, SCHEMA.num_numeric)).astype(np.float32)
        engine.predict_arrays(cat, num)  # warm this bucket
        t0 = time.perf_counter()
        for _ in range(reps):
            engine.predict_arrays(cat, num)
        dt = time.perf_counter() - t0
        out[f"bulk_rows_per_s_b{n}"] = round(reps * n / dt, 1)
    _note("bulk pipelined sweep")

    # Pipelined sweep: 262,144 rows through the chunked bulk scorer —
    # once exact (serving-identical ensemble; the key's historical
    # meaning) and once auto-routed (the product path: the distilled bulk
    # student on CPU backends, the exact model on TPU — parallel/bulk.py
    # use_distilled_bulk). The auto number is the one BASELINE.md compares
    # against the sklearn GBM floor.
    n = 262_144
    ds = EncodedDataset(
        cat_ids=rng.integers(0, 2, (n, SCHEMA.num_categorical)).astype(np.int32),
        numeric=rng.normal(size=(n, SCHEMA.num_numeric)).astype(np.float32),
        labels=None,
    )
    from mlops_tpu.parallel.bulk import use_distilled_bulk

    result = score_dataset(bundle, ds, mesh=None, chunk_rows=16_384, exact=True)
    out["bulk_rows_per_s_pipelined"] = round(result.rows_per_s, 1)
    if use_distilled_bulk(bundle):
        # Only re-sweep when auto actually routes differently (distilled
        # student on CPU); on the exact path the number would be a
        # duplicate measurement plus a duplicate compile.
        auto = score_dataset(bundle, ds, mesh=None, chunk_rows=16_384)
        out["bulk_rows_per_s_bulkpath"] = round(auto.rows_per_s, 1)
        out["bulk_path"] = auto.path
    else:
        out["bulk_rows_per_s_bulkpath"] = out["bulk_rows_per_s_pipelined"]
        out["bulk_path"] = "exact"
    fidelity = bundle.bulk_fidelity
    if "roc_auc_delta" in fidelity:
        out["bulk_fidelity_auc_delta"] = round(fidelity["roc_auc_delta"], 4)

    # Quant tier sweep (ISSUE 17): the int8/bf16 student through the same
    # chunked scorer. quant_auc_delta is the STAMPED held-out fidelity
    # (student AUC minus teacher AUC, post-quantization — the number the
    # promotion gate graded), not re-measured on this unlabeled synthetic
    # sweep; quant_speedup_vs_student is the acceptance ratio vs the f32
    # bulk path the sweep above just measured.
    if bundle.has_quant and bundle.quant_gates_passed:
        _note("bulk quant sweep")
        quant = score_dataset(
            bundle, ds, mesh=None, chunk_rows=16_384, tier="quant"
        )
        out["quant_rows_per_s"] = round(quant.rows_per_s, 1)
        out["quant_speedup_vs_student"] = round(
            quant.rows_per_s
            / max(out["bulk_rows_per_s_bulkpath"], 1e-9), 2
        )
        qfid = bundle.quant_fidelity
        if "roc_auc_delta" in qfid:
            out["quant_auc_delta"] = round(qfid["roc_auc_delta"], 4)
    return out


def _stream_stage(bundle) -> dict:
    """Pipelined streaming-executor sweep (data/pipeline_exec.py): score a
    synthetic 200k-row CSV through `score_csv_stream` three ways —

    - ``serial``: the pre-executor baseline (depth 1, Python csv parse —
      exactly the old chunk loop's behavior),
    - ``native_serial``: depth 1 with the native C++ chunk encode (the
      kernel-side win in isolation),
    - ``pipelined``: depth 2 with native encode — the product path, with
      read / encode / transfer / compute / fetch / write overlapped on
      bounded queues.

    Reports rows/s for each, the end-to-end speedup (pipelined vs the old
    serial path), the overlap-only speedup (pipelined vs native serial —
    bounded by how much real CPU parallelism the host offers), per-stage
    occupancies from the pipelined run, and an output bit-identity check
    across all three (the executor preserves chunk order, so any depth
    must produce the same file)."""
    import tempfile
    from pathlib import Path

    from mlops_tpu.data import generate_synthetic, write_csv_columns
    from mlops_tpu.data.stream import score_csv_stream

    n = 200_000
    depth = 2
    columns, labels = generate_synthetic(n, seed=5)
    out: dict = {"bulk_stream_rows": n, "bulk_stream_pipeline_depth": depth}
    with tempfile.TemporaryDirectory() as td:
        data_path = Path(td) / "stream.csv"
        write_csv_columns(data_path, columns, labels)
        _note("stream sweep: serial (python parse, depth 1)")
        serial = score_csv_stream(
            bundle, data_path, Path(td) / "serial.csv",
            chunk_rows=16_384, pipeline_depth=1, native=False,
        )
        _note("stream sweep: native serial (depth 1)")
        native_serial = score_csv_stream(
            bundle, data_path, Path(td) / "native.csv",
            chunk_rows=16_384, pipeline_depth=1,
        )
        _note(f"stream sweep: pipelined (native, depth {depth})")
        pipelined = score_csv_stream(
            bundle, data_path, Path(td) / "pipelined.csv",
            chunk_rows=16_384, pipeline_depth=depth,
        )
        out["bulk_stream_outputs_identical"] = (
            (Path(td) / "serial.csv").read_bytes()
            == (Path(td) / "native.csv").read_bytes()
            == (Path(td) / "pipelined.csv").read_bytes()
        )
    out["bulk_stream_rows_per_s_serial"] = serial["rows_per_s"]
    out["bulk_stream_rows_per_s_native_serial"] = native_serial["rows_per_s"]
    out["bulk_stream_rows_per_s_pipelined"] = pipelined["rows_per_s"]
    out["bulk_stream_speedup"] = round(
        pipelined["rows_per_s"] / max(serial["rows_per_s"], 1e-9), 3
    )
    out["bulk_stream_overlap_speedup"] = round(
        pipelined["rows_per_s"] / max(native_serial["rows_per_s"], 1e-9), 3
    )
    out["bulk_stream_path"] = pipelined["path"]
    out["bulk_stream_stage_occupancy"] = {
        name: timing["occupancy"]
        for name, timing in pipelined["stages"].items()
    }
    return out


def _mfu_stage(bundle, bulk: dict, device) -> dict:
    """Roofline evidence (SURVEY §6 gap: the reference publishes none):
    XLA-counted FLOPs per call ÷ measured wall ÷ chip peak, for the three
    hot paths — bulk inference (using the throughput the bulk stage just
    measured), one fused train step at the training batch size, and the
    flash-attention kernel at its tuned shape. The peak denominator is
    the device's published spec when known, the user's
    ``MLOPS_TPU_PEAK_FLOPS`` when set (``peak_source: "env"``), or — on
    a plain CPU — the host's MEASURED dense-GEMM rate
    (``peak_source: "measured-gemm"``); only an unknown non-CPU device
    leaves ``mfu_*`` None. ``*_gflops_per_s`` is always reported so the
    achieved-FLOPs floor is auditable regardless."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlops_tpu.schema import SCHEMA
    from mlops_tpu.utils.flops import (
        compile_with_flops,
        compiled_flops,
        measured_gemm_peak,
        mfu,
        peak_flops,
    )

    if bundle.flavor == "sklearn":
        return {}

    def peak_for(dtype: str) -> tuple[float | None, str]:
        """Peak at the stated EXECUTING precision (ISSUE 17 mfu fix: an
        f32 program divided by the bf16 spec peak understates MFU 2x)."""
        if os.environ.get("MLOPS_TPU_PEAK_FLOPS"):
            return peak_flops(device, dtype), "env"
        p = peak_flops(device, dtype)
        if p is not None:
            return p, "spec"
        if getattr(device, "platform", "") == "cpu":
            # No published peak for arbitrary host silicon: measure the
            # backend's own dense-GEMM rate AT THIS PRECISION and report
            # MFU against that — "fraction of this host's measured
            # matmul peak".
            return measured_gemm_peak(dtype=dtype), "measured-gemm"
        return None, "unknown"

    # The bulk/train programs execute f32 end to end — the quant tier too
    # (it dequantizes in-jit; int8 saves HBM bytes, not MXU precision).
    # Only the flash-attention kernel below runs bf16. Each mfu_* key
    # records the precision its denominator was taken at.
    peak, peak_source = peak_for("f32")
    out: dict = {
        "peak_flops": peak,
        "peak_source": peak_source,
        "mfu_bulk_dtype": "float32",
        "mfu_train_dtype": "float32",
    }

    model, variables = bundle.model, bundle.variables
    rng = np.random.default_rng(1)

    # Each section guards itself — INCLUDING its input construction — so
    # a failure in one never discards the evidence the others produced.
    n = 16_384

    def big_inputs():
        cat = jnp.asarray(
            rng.integers(0, 2, (n, SCHEMA.num_categorical)).astype(np.int32)
        )
        num = jnp.asarray(
            rng.normal(size=(n, SCHEMA.num_numeric)).astype(np.float32)
        )
        return cat, num

    # --- bulk inference: FLOPs of the SAME fused program the bulk stage
    # timed (classifier + drift + outlier, ops/predict.py) × measured
    # calls/s — numerator and denominator must describe one program.
    try:
        from mlops_tpu.ops.predict import make_padded_predict_fn

        cat, num = big_inputs()
        mask = jnp.ones((n,), bool)
        fused = make_padded_predict_fn(
            model, variables, bundle.monitor, bundle.temperature
        )
        f_bulk = compiled_flops(fused, cat, num, mask)
        rows_per_s = bulk.get("bulk_rows_per_s_b16384", 0.0)
        if f_bulk:
            out["bulk_gflops_per_s"] = round(f_bulk * rows_per_s / n / 1e9, 1)
            out["mfu_bulk"] = mfu(f_bulk, rows_per_s / n, peak)
    except Exception as err:
        out["mfu_bulk_error"] = f"{type(err).__name__}: {err}"

    # --- train step: fused value_and_grad at the training batch size.
    try:
        from mlops_tpu.train.loop import training_loss

        batch = 1024
        tcat = jnp.asarray(
            rng.integers(0, 2, (batch, SCHEMA.num_categorical)).astype(np.int32)
        )
        tnum = jnp.asarray(
            rng.normal(size=(batch, SCHEMA.num_numeric)).astype(np.float32)
        )
        tlab = jnp.asarray((rng.random(batch) < 0.2).astype(np.float32))
        key = jax.random.PRNGKey(0)

        def step(params, cat, num, lab):
            return jax.value_and_grad(
                lambda p: training_loss(model, p, cat, num, lab, key, 1.0)
            )(params)

        params = variables["params"]
        # One compile serves both the FLOP count and the timed calls.
        exe, f_step = compile_with_flops(step, params, tcat, tnum, tlab)
        if exe is not None:
            jax.block_until_ready(exe(params, tcat, tnum, tlab))
            reps = 10
            t0 = time.perf_counter()
            for _ in range(reps):
                loss, grads = exe(params, tcat, tnum, tlab)
            jax.block_until_ready(grads)
            dt = (time.perf_counter() - t0) / reps
            if f_step:
                out["train_step_gflops_per_s"] = round(f_step / dt / 1e9, 1)
                out["mfu_train"] = mfu(f_step, 1.0 / dt, peak)
    except Exception as err:
        out["mfu_train_error"] = f"{type(err).__name__}: {err}"

    # --- flash attention at its tuned shape (TPU only: the Pallas kernel
    # runs in interpret mode on CPU, which measures the interpreter).
    # Guarded: roofline extras must never cost the run its headline
    # numbers (this block only ever executes on a live chip).
    if getattr(device, "platform", "cpu") != "cpu":
        try:
            from mlops_tpu.ops.attention import flash_attention

            peak_bf16, _ = peak_for("bf16")
            out["mfu_flash_attn_dtype"] = "bfloat16"
            b, s, h, d = 4, 2048, 8, 64
            q, k, v = (
                jnp.asarray(
                    rng.normal(size=(b, s, h, d)), dtype=jnp.bfloat16
                )
                for _ in range(3)
            )
            flash = jax.jit(flash_attention)
            jax.block_until_ready(flash(q, k, v))
            reps = 20
            t0 = time.perf_counter()
            for _ in range(reps):
                o = flash(q, k, v)
            jax.block_until_ready(o)
            dt = (time.perf_counter() - t0) / reps
            # Analytic dense-equivalent FLOPs (QKᵀ + PV): Pallas kernels
            # are opaque to XLA's cost model, so this one is counted by
            # hand.
            f_attn = 4.0 * b * h * s * s * d
            out["flash_attn_gflops_per_s"] = round(f_attn / dt / 1e9, 1)
            out["mfu_flash_attn"] = mfu(f_attn, 1.0 / dt, peak_bf16)

            # Forward+backward through the Pallas VJP (round 5): the
            # backward recomputes p from the stored logsumexp in two
            # kernels — dense-equivalent FLOPs are 2.5x the forward's
            # (fwd QKᵀ+PV, bwd dq+dkv ≈ 5 matmuls of the same shape).
            # Own guard: a backward-only failure must not discard the
            # forward numbers above nor skip the s4096 comparison below.
            try:
                grad_fn = jax.jit(
                    jax.grad(
                        lambda q, k, v: flash_attention(q, k, v)
                        .astype(jnp.float32)
                        .sum(),
                        argnums=(0, 1, 2),
                    )
                )
                jax.block_until_ready(grad_fn(q, k, v))
                t0 = time.perf_counter()
                for _ in range(reps):
                    g = grad_fn(q, k, v)
                jax.block_until_ready(g)
                dt_g = (time.perf_counter() - t0) / reps
                f_train = f_attn * 3.5  # fwd (2 matmuls) + bwd (5 matmuls)
                out["flash_attn_bwd_ms"] = round(dt_g * 1e3, 3)
                out["mfu_flash_attn_train"] = mfu(f_train, 1.0 / dt_g, peak_bf16)
            except Exception as err:
                out["flash_attn_bwd_error"] = f"{type(err).__name__}: {err}"

            # seq-4096 head-to-head (VERDICT r4 #5's "done" evidence):
            # the Pallas backward vs the dense O(S²)-remat VJP it
            # replaced, same shape. Dense materializes the [H,S,S] score
            # tensor twice (fwd rebuild + softmax vjp) — each
            # measurement is separately guarded so a dense OOM records
            # as its own error string, not a lost flash number.
            b4, s4 = 2, 4096
            q4, k4, v4 = (
                jnp.asarray(
                    rng.normal(size=(b4, s4, h, d)), dtype=jnp.bfloat16
                )
                for _ in range(3)
            )

            def timed_grad(fn, reps=5):
                gfn = jax.jit(
                    jax.grad(
                        lambda q, k, v: fn(q, k, v)
                        .astype(jnp.float32)
                        .sum(),
                        argnums=(0, 1, 2),
                    )
                )
                jax.block_until_ready(gfn(q4, k4, v4))
                t0 = time.perf_counter()
                for _ in range(reps):
                    g = gfn(q4, k4, v4)
                jax.block_until_ready(g)
                return (time.perf_counter() - t0) / reps

            try:
                out["flash_bwd_s4096_ms"] = round(
                    timed_grad(flash_attention) * 1e3, 2
                )
            except Exception as err:
                out["flash_bwd_s4096_error"] = f"{type(err).__name__}: {err}"
            try:
                from mlops_tpu.ops.attention import reference_attention

                out["dense_bwd_s4096_ms"] = round(
                    timed_grad(reference_attention) * 1e3, 2
                )
            except Exception as err:
                out["dense_bwd_s4096_error"] = f"{type(err).__name__}: {err}"
        except Exception as err:
            out["mfu_flash_attn_error"] = f"{type(err).__name__}: {err}"
    return out


_COLDSTART_PROBE = r"""
import json, sys, time
from mlops_tpu.commands import _honor_jax_platforms_env
_honor_jax_platforms_env()
from mlops_tpu.bundle import load_bundle
from mlops_tpu.compilecache import CompileCache
from mlops_tpu.serve.engine import InferenceEngine

bundle_dir, cache_dir = sys.argv[1], sys.argv[2]
bundle = load_bundle(bundle_dir)
engine = InferenceEngine(bundle, compile_cache=CompileCache(cache_dir))
t0 = time.perf_counter()
engine.warmup()
print(json.dumps({
    "warmup_s": round(time.perf_counter() - t0, 3),
    "cache": engine.warmup_stats["cache"],
}))
"""


def _coldstart_stage(bundle_dir) -> dict:
    """The deploy-path cold-start evidence (compilecache/): warm a FRESH
    process's engine twice against one AOT executable cache dir — the
    first process compiles every bucket/group program and persists
    (``engine_cold_start_s``, all misses), the second deserializes
    (``engine_warm_start_s``, all hits). The ratio is what every rollout,
    autoscale event, and restart saves; separate processes are the point
    (jit caches don't survive a process, the artifact cache does)."""
    import subprocess
    import tempfile

    out: dict = {}
    with tempfile.TemporaryDirectory() as cache_dir:
        for phase in ("cold", "warm"):
            _note(f"engine {phase} start probe (fresh process)")
            proc = subprocess.run(
                [sys.executable, "-c", _COLDSTART_PROBE,
                 str(bundle_dir), cache_dir],
                capture_output=True,
                text=True,
                timeout=600,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            if proc.returncode != 0:
                raise RuntimeError(
                    f"{phase} start probe failed: {proc.stderr[-500:]}"
                )
            probe = json.loads(proc.stdout.strip().splitlines()[-1])
            cache = probe["cache"] or {}
            out[f"engine_{phase}_start_s"] = probe["warmup_s"]
            out[f"engine_{phase}_start_cache_hits"] = cache.get("hits", 0)
            out[f"engine_{phase}_start_cache_misses"] = cache.get("misses", 0)
            bypasses = cache.get("bypasses", 0)
            if bypasses:
                out[f"engine_{phase}_start_cache_bypasses"] = bypasses
    out["engine_warm_start_speedup"] = round(
        out["engine_cold_start_s"] / max(out["engine_warm_start_s"], 1e-9), 2
    )
    return out


def _engine_stage(engine, record) -> dict:
    """Chip-serving capability without the HTTP layer: concurrent grouped
    dispatches from a small thread pool (what replica processes would
    drive). Separates the device ceiling from server-side Python cost."""
    if not engine.supports_grouping:
        return {}
    reqs = [[record]] * 64
    engine.predict_group(reqs)  # warm
    n_threads, reps = 4, 5

    def worker():
        for _ in range(reps):
            engine.predict_group(reqs)

    threads = [_threading.Thread(target=worker) for _ in range(n_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    return {"engine_group_req_per_s": round(n_threads * reps * 64 / dt, 1)}


def _batcher_mode_stage(engine, record) -> dict:
    """Continuous vs windowed micro-batching (ISSUE 17): per-request p50
    for batch-1 bodies THROUGH the MicroBatcher under concurrent load (8
    overlapped clients — batch-1 sequential traffic rides the batcher's
    idle fast-path in both modes, so only concurrency exposes the
    admission policy). The windowed wave holds every group open for the
    full ``window_ms`` before dispatching; continuous admits at dispatch
    boundaries (zero wait while groups are in flight, a measured
    EWMA-derived deadline on an empty pipe), so its p50 sheds most of the
    fixed window. Responses are bit-identical across modes
    (tests/test_batcher.py pins it); this stage records the latency
    consequence."""
    import asyncio
    from concurrent.futures import ThreadPoolExecutor

    from mlops_tpu.serve.batcher import MicroBatcher

    if not engine.supports_grouping:
        return {}

    async def run(mode: str) -> tuple[list[float], float]:
        lat: list[float] = []
        with ThreadPoolExecutor(max_workers=8) as pool:
            batcher = MicroBatcher(
                engine, pool, window_ms=1.0, batch_mode=mode
            )
            loop = asyncio.get_running_loop()

            async def client(n: int) -> None:
                for _ in range(n):
                    t0 = loop.time()
                    await batcher.predict([record])
                    lat.append((loop.time() - t0) * 1e3)

            await asyncio.gather(*[client(5) for _ in range(8)])  # warm
            lat.clear()
            await asyncio.gather(*[client(25) for _ in range(8)])
            # Drain stragglers so the pool shutdown never strands a task.
            while batcher._dispatch_tasks:
                await asyncio.sleep(0.001)
            admit_ms = batcher._admit_deadline_s() * 1e3
        lat.sort()
        return lat, admit_ms

    out: dict = {}
    for mode in ("windowed", "continuous"):
        lat, admit_ms = asyncio.run(run(mode))
        out[f"batch1_p50_ms_{mode}"] = round(_percentile(lat, 50), 4)
        out[f"batch1_p99_ms_{mode}"] = round(_percentile(lat, 99), 4)
        if mode == "continuous":
            # The measured empty-pipe admit deadline the EWMA settled on
            # (the windowed mode's equivalent is the fixed 1.0 window).
            out["batch1_admit_deadline_ms"] = round(admit_ms, 4)
    return out


_HTTP_CLIENT = r"""
import asyncio, json, sys, time

port = int(sys.argv[1])
body = sys.stdin.buffer.read()
head = (
    "POST /predict HTTP/1.1\r\nhost: x\r\n"
    "content-type: application/json\r\n"
    f"content-length: {len(body)}\r\n\r\n"
).encode()


counts = {"ok": 0, "shed": 0}


async def client(n_requests):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    for _ in range(n_requests):
        writer.write(head + body)
        await writer.drain()
        line = await reader.readline()
        status = int(line.split(b" ")[1])
        length = 0
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n"):
                break
            if h.lower().startswith(b"content-length:"):
                length = int(h.split(b":")[1])
        await reader.readexactly(length)
        # GOODPUT accounting: 200s count toward the rate; shed 503s are
        # surfaced separately (the single-process server never sheds, so
        # its numbers keep their historical meaning); anything else is a
        # hard failure.
        if status == 200:
            counts["ok"] += 1
        elif status == 503:
            counts["shed"] += 1
        else:
            raise AssertionError(line)
    writer.close()
    try:
        await writer.wait_closed()
    except (ConnectionResetError, BrokenPipeError):
        pass


async def main():
    results = {}
    for concurrency, per_client in ((1, 20), (8, 15), (32, 10), (128, 8)):
        await asyncio.gather(*[client(3) for _ in range(min(concurrency, 4))])
        counts["ok"] = counts["shed"] = 0
        t0 = time.perf_counter()
        await asyncio.gather(*[client(per_client) for _ in range(concurrency)])
        dt = time.perf_counter() - t0
        results[f"http_req_per_s_c{concurrency}"] = round(
            counts["ok"] / dt, 1
        )
        if counts["shed"]:
            results[f"http_shed_c{concurrency}"] = counts["shed"]
    print(json.dumps(results))


asyncio.run(main())
"""


def _autotune_stage(bundle, record) -> dict:
    """Gridtuner evidence (ISSUE 18): a skewed synthetic trace is driven
    on a deliberately coarse hand-picked grid with the shape table and
    cost ledger armed; the autotuner fits the measured cost model,
    searches, and hot-applies the winning grid under a live request
    hammer. Keys:

    - ``autotune_goodput_gain_pct`` — measured useful-rows/s gain of
      the autotuned grid over the hand grid on the SAME trace (the
      acceptance headline: autotuned must beat hand-picked);
    - ``regrid_downtime_ms`` — worst hammer-observed request latency
      overlapping the swap minus the pre-swap p50 (the ~0 ms claim,
      measured: warm happens off-path first, the swap is a pointer
      re-point under the existing locks);
    - ``autotune_predicted_gain_pct`` / ``autotune_buckets`` /
      ``autotune_*_waste_pct`` — the plan's own claim, so committed
      rounds carry the predicted-vs-measured audit.
    """
    import tempfile

    from mlops_tpu.autotune import (
        apply_plan,
        demand_from_shapes,
        fit_cost_model,
        ledger_rows_from_snapshot,
        warm_plan,
    )
    from mlops_tpu.autotune.search import search_plan
    from mlops_tpu.serve.engine import InferenceEngine
    from mlops_tpu.slo.ledger import CostLedger
    from mlops_tpu.trace.shapes import ShapeStats

    # A coarse hand grid for the trace below — the 40-row mode pads
    # 12.8x on bucket_512. Grouping off: the gridtuner's search space
    # is the solo grid (group geometry is a fixed module constant).
    engine = InferenceEngine(
        bundle, buckets=(512, 4096), enable_grouping=False
    )
    engine.warmup()
    stats = ShapeStats()
    ledger = CostLedger(
        tempfile.mkdtemp(prefix="bench-autotune-"), flush_interval_s=1e6
    )
    engine.set_shape_stats(stats)
    engine.set_cost_ledger(ledger)
    # Skewed synthetic demand: a dominant small mode, a mid mode, and a
    # rare near-ceiling tail (the shape real credit traffic shows).
    trace = ([40] * 18 + [400] * 3 + [3800] * 1) * 6
    reqs = {n: [record] * n for n in set(trace)}

    def drive() -> float:
        t0 = time.perf_counter()
        rows = 0
        for n in trace:
            engine.predict_records(reqs[n])
            rows += n
        return rows / (time.perf_counter() - t0)

    useful_before = drive()
    model = fit_cost_model(ledger_rows_from_snapshot(ledger.snapshot()))
    plan = search_plan(
        demand_from_shapes(stats.snapshot()),
        model,
        tuple(engine.buckets),
        max_entries=16,
    )
    # Warm off-path BEFORE the hammer window so the measured downtime is
    # the swap itself, not compile contention (the controller's order).
    warm_plan(engine, plan.buckets)

    hammer_lat: list[tuple[float, float]] = []
    hammer_stop = _threading.Event()
    hreq = reqs[40]

    def hammer():
        while not hammer_stop.is_set():
            h0 = time.perf_counter()
            engine.predict_records(hreq)
            hammer_lat.append((h0, time.perf_counter()))

    ht = _threading.Thread(target=hammer, daemon=True)
    ht.start()
    time.sleep(0.3)  # settle: a pre-swap latency baseline
    s0 = time.perf_counter()
    apply_plan(engine, plan.buckets)
    s1 = time.perf_counter()
    time.sleep(0.1)
    hammer_stop.set()
    ht.join(timeout=10)
    pre = sorted(e - b for b, e in hammer_lat if e <= s0)
    overlap = [e - b for b, e in hammer_lat if e > s0 and b < s1]
    p50_pre = pre[len(pre) // 2] if pre else 0.0
    downtime_ms = (
        max(0.0, (max(overlap) - p50_pre) * 1e3) if overlap else 0.0
    )
    useful_after = drive()
    out = {
        "autotune_goodput_gain_pct": round(
            100.0 * (useful_after - useful_before) / useful_before, 2
        ),
        "regrid_downtime_ms": round(downtime_ms, 3),
        "autotune_predicted_gain_pct": round(plan.predicted_gain_pct, 2),
        "autotune_buckets": list(plan.buckets),
        "autotune_baseline_waste_pct": round(plan.baseline_waste_pct, 2),
        "autotune_waste_pct": round(plan.predicted_waste_pct, 2),
    }
    engine.rollback()
    ledger.close()
    return out


def _http_stage(engine, record) -> dict:
    """req/s through the real HTTP server + micro-batcher at client
    concurrency {1, 8, 32, 128} (keep-alive, batch-1 bodies). The load
    generator runs in a SEPARATE process — clients sharing the server's
    event loop would throttle the server and measure the harness, not
    the service. These are the ``http_workers=1`` axis points; the
    multi-worker plane's points come from `_http_multi_stage`."""
    import asyncio
    import subprocess

    from mlops_tpu.config import ServeConfig
    from mlops_tpu.serve.server import HttpServer

    body = json.dumps([record]).encode()

    async def run() -> dict:
        config = ServeConfig(host="127.0.0.1", port=0)
        server = HttpServer(engine, config)
        srv = await asyncio.start_server(server.handle_connection, "127.0.0.1", 0)
        port = srv.sockets[0].getsockname()[1]
        proc = await asyncio.create_subprocess_exec(
            sys.executable,
            "-c",
            _HTTP_CLIENT,
            str(port),
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
        )
        out, _ = await proc.communicate(body)
        srv.close()
        await srv.wait_closed()
        if proc.returncode != 0:
            raise RuntimeError("http load client failed")
        return json.loads(out.decode().strip().splitlines()[-1])

    results = asyncio.run(run())
    # The workers axis aliases: http_req_per_s_c{N} keeps its historical
    # meaning (single-process server) AND doubles as http_w1_*.
    results.update(
        {k.replace("http_req_per_s", "http_w1_req_per_s"): v
         for k, v in list(results.items())}
    )
    return results


_BURST_CLIENT = r"""
import asyncio, json, sys, time

port, concurrency, per_client = (int(a) for a in sys.argv[1:4])
body = sys.stdin.buffer.read()
head = (
    "POST /predict HTTP/1.1\r\nhost: x\r\n"
    "content-type: application/json\r\n"
    f"content-length: {len(body)}\r\n\r\n"
).encode()
counts = {"ok": 0, "shed": 0, "other": 0, "errors": 0}


async def client():
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
    except OSError:
        counts["errors"] += per_client
        return
    try:
        for _ in range(per_client):
            writer.write(head + body)
            await writer.drain()
            line = await reader.readline()
            status = int(line.split(b" ")[1])
            length = 0
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"\n"):
                    break
                if h.lower().startswith(b"content-length:"):
                    length = int(h.split(b":")[1])
            await reader.readexactly(length)
            if status == 200:
                counts["ok"] += 1
            elif status == 503:
                counts["shed"] += 1
            else:
                counts["other"] += 1
    except (OSError, asyncio.IncompleteReadError, ValueError):
        counts["errors"] += 1
    finally:
        writer.close()


async def main():
    t0 = time.perf_counter()
    await asyncio.gather(*[client() for _ in range(concurrency)])
    counts["wall_s"] = round(time.perf_counter() - t0, 3)
    print(json.dumps(counts))


asyncio.run(main())
"""


def _http_multi_stage(engine, bundle, record, base: dict) -> dict:
    """The multi-worker plane's points on the ``http_workers`` axis
    (workers in {2, 4}: SO_REUSEPORT front-end processes + the
    shared-memory ring into THIS process's engine — serve/frontend.py),
    the ``http_vs_engine_ratio`` derived key (best HTTP req/s at any
    workers/concurrency over the engine's direct grouped capability:
    1.0 means the server plane no longer hides the engine), and the
    ``shed_503_pct`` key from an overload burst at 10x the
    best-concurrency offered load (fast 503s are the contract; errors or
    stalls are not)."""
    import dataclasses
    import subprocess
    import tempfile

    from mlops_tpu.config import ServeConfig
    from mlops_tpu.serve.frontend import reuseport_socket, start_frontends
    from mlops_tpu.serve.ipc import RequestRing, RingService

    body = json.dumps([record]).encode()
    out: dict = {}

    def run_client(script: str, port: int, *args: int) -> dict:
        proc = subprocess.run(
            [sys.executable, "-c", script, str(port),
             *(str(a) for a in args)],
            input=body, stdout=subprocess.PIPE, timeout=600,
        )
        if proc.returncode != 0:
            raise RuntimeError("http load client failed")
        return json.loads(proc.stdout.decode().strip().splitlines()[-1])

    with tempfile.TemporaryDirectory() as td:
        prep_path = os.path.join(td, "preprocess.npz")
        bundle.preprocessor.save(prep_path)
        for workers in (2, 4):
            _note(f"http multi stage: workers={workers}")
            # Ring sized so the c128 grid point fits admission even under
            # maximally skewed kernel connection hashing: the grid
            # measures throughput; the overload burst below measures
            # shedding.
            cfg = ServeConfig(
                host="127.0.0.1", port=0, workers=workers,
                ring_slots_small=128,
            ).validate()
            ring = RequestRing(
                workers=workers,
                slots_small=cfg.ring_slots_small,
                slots_large=cfg.ring_slots_large,
                large_rows=cfg.max_batch,
            )
            placeholder = reuseport_socket(cfg.host, cfg.port)
            child_cfg = dataclasses.replace(
                cfg, port=placeholder.getsockname()[1]
            )
            procs = start_frontends(child_cfg, ring, prep_path)
            service = RingService(
                engine, ring,
                max_group=cfg.max_group,
                max_inflight=cfg.max_inflight,
                threads=cfg.max_workers,
            )
            service.start()
            ring.set_ready(True)
            try:
                _wait_port(child_cfg.port)
                results = run_client(_HTTP_CLIENT, child_cfg.port)
                # Prefix EVERY client key (req_per_s AND shed counts)
                # into this workers-axis namespace: an unprefixed
                # http_shed_c* would collide across axis points and read
                # as a single-process anomaly in the trajectory.
                out.update(
                    {
                        k.replace("http_", f"http_w{workers}_", 1): v
                        for k, v in results.items()
                    }
                )
                if workers == 2:
                    # Overload burst: 10x the best concurrency as offered
                    # connections, one request each (capped — the point is
                    # admission behavior, not fd exhaustion).
                    grid = {
                        int(k.rsplit("c", 1)[1]): v
                        for k, v in {**base, **out}.items()
                        if "_req_per_s_c" in k
                    }
                    best_c = max(grid, key=grid.get) if grid else 32
                    offered = min(10 * best_c, 640)
                    burst = run_client(
                        _BURST_CLIENT, child_cfg.port, offered, 1
                    )
                    total = max(
                        burst["ok"] + burst["shed"] + burst["other"], 1
                    )
                    out["shed_burst_offered"] = offered
                    out["shed_503_pct"] = round(
                        100.0 * burst["shed"] / total, 1
                    )
                    out["shed_burst_ok"] = burst["ok"]
                    out["shed_burst_errors"] = burst["errors"]
            finally:
                ring.set_draining()
                ring.set_ready(False)
                for proc in procs:
                    if proc.is_alive() and proc.pid:
                        os.kill(proc.pid, 15)
                for proc in procs:
                    proc.join(timeout=15)
                    if proc.is_alive():
                        proc.terminate()
                        proc.join(timeout=5)
                service.stop()
                placeholder.close()
                ring.close()

    rates = {
        k: v
        for k, v in {**base, **out}.items()
        if "_req_per_s_c" in k and isinstance(v, (int, float))
    }
    if rates:
        best_key = max(rates, key=rates.get)
        out["http_req_per_s_best"] = rates[best_key]
        out["http_best_point"] = best_key
        group_rate = base.get("engine_group_req_per_s")
        if group_rate:
            out["http_vs_engine_ratio"] = round(
                rates[best_key] / group_rate, 3
            )
    return out


_BROWNOUT_CLIENT = r"""
import asyncio, json, sys, time

port, concurrency = int(sys.argv[1]), int(sys.argv[2])
duration_s, backoff_s = float(sys.argv[3]), float(sys.argv[4])
body = sys.stdin.buffer.read()
head = (
    "POST /predict HTTP/1.1\r\nhost: x\r\n"
    "content-type: application/json\r\n"
    f"content-length: {len(body)}\r\n\r\n"
).encode()
counts = {"ok": 0, "shed": 0, "other": 0, "errors": 0}
deadline = time.perf_counter() + duration_s


async def client():
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
    except OSError:
        counts["errors"] += 1
        return
    try:
        while time.perf_counter() < deadline:
            writer.write(head + body)
            await writer.drain()
            line = await reader.readline()
            status = int(line.split(b" ")[1])
            length = 0
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"\n"):
                    break
                if h.lower().startswith(b"content-length:"):
                    length = int(h.split(b":")[1])
            await reader.readexactly(length)
            if status == 200:
                counts["ok"] += 1
            elif status == 503:
                counts["shed"] += 1
                # honor the shed's Retry-After spirit: back off instead
                # of hammering the admission edge with instant retries
                await asyncio.sleep(backoff_s)
            else:
                counts["other"] += 1
    except (OSError, asyncio.IncompleteReadError, ValueError):
        counts["errors"] += 1
    finally:
        writer.close()


async def main():
    t0 = time.perf_counter()
    await asyncio.gather(*[client() for _ in range(concurrency)])
    counts["wall_s"] = round(time.perf_counter() - t0, 3)
    print(json.dumps(counts))


asyncio.run(main())
"""


def _tierroute_stage(bundle, record) -> dict:
    """Tiered SLO serving evidence (serve/tierroute.py, ISSUE 19) in two
    measurements:

    - per-class routed throughput on a `tier_routing=True` engine
      (``tier_req_per_s_{default,cheap,accurate}`` + the headline
      ``tier_routed_req_per_s`` = the cheap class through its routed
      tier) — cheap rides the gated quant student, accurate pins exact;
    - a 10x-overload A/B on a live 1-worker plane with the SAME engine:
      brownout-on (tier_routing, default traffic demotes at
      `brownout_demote_depth` occupancy) vs brownout-off (pure shed),
      compared on useful responses/s —
      ``brownout_goodput_gain_pct`` is the headline, plus the raw
      ok/shed/demotion counts for both arms.
    """
    import dataclasses
    import subprocess
    import tempfile

    from mlops_tpu.config import ServeConfig
    from mlops_tpu.serve.engine import InferenceEngine
    from mlops_tpu.serve.frontend import reuseport_socket, start_frontends
    from mlops_tpu.serve.ipc import RequestRing, RingService
    from mlops_tpu.serve.tierroute import SLO_ACCURATE, SLO_CHEAP

    if not (bundle.has_quant and bundle.quant_gates_passed):
        return {"tierroute_skipped": "bundle has no gate-passed quant tier"}

    routed = InferenceEngine(bundle, buckets=(1, 8, 64), tier_routing=True)
    routed.warmup()
    out: dict = {"tier_ladder": list(routed.available_tiers)}

    # Per-class routed throughput: the class->tier mapping the plane
    # would apply, measured on the engine's own dispatch path.
    for label, slo in (
        ("default", None),
        ("cheap", SLO_CHEAP),
        ("accurate", SLO_ACCURATE),
    ):
        tier = routed.route_tier(slo) if slo is not None else None
        if tier is None:
            p50 = _p50_ms(lambda: routed.predict_records([record]))
        else:
            p50 = _p50_ms(
                lambda t=tier: routed.predict_records([record], tier=t)
            )
        out[f"tier_req_per_s_{label}"] = round(1e3 / p50, 1)
    out["tier_routed_req_per_s"] = out["tier_req_per_s_cheap"]

    # Brownout-vs-shed A/B: one worker, a small slot partition, a
    # closed-loop fleet of 10x-partition clients hammering for a fixed
    # window (503s back off per the Retry-After contract). The offered
    # unit is a 64-ROW request — past GROUP_ROW_BUCKET, so each request
    # is one solo device dispatch and the default tier's compute (not
    # the HTTP edge) is the contended resource; demoting to the quant
    # student is then a real capacity change, which is exactly the
    # brownout claim. Same engine, same ring geometry — the only
    # difference between arms is serve.tier_routing (the governor arms
    # with it), so any goodput delta is the demotion path. The demote
    # depth is drill-tuned to the tiny partition (3 of 6 slots busy
    # activates) the way chaos_smoke tunes its plane.
    rows = 64
    body = json.dumps([record] * rows).encode()
    slots_small, slots_large = 1, 5
    partition = slots_small + slots_large
    concurrency = 10 * partition
    duration_s = 8.0
    backoff_s = 0.3
    arms: dict[str, dict] = {}
    with tempfile.TemporaryDirectory() as td:
        prep_path = os.path.join(td, "preprocess.npz")
        bundle.preprocessor.save(prep_path)
        for arm, routing in (("on", True), ("off", False)):
            _note(f"tierroute stage: brownout {arm}")
            cfg = ServeConfig(
                host="127.0.0.1", port=0, workers=1,
                ring_slots_small=slots_small,
                ring_slots_large=slots_large,
                max_batch=rows,
                tier_routing=routing,
                brownout_demote_depth=0.5,
                brownout_restore_depth=0.25,
            ).validate()
            ring = RequestRing(
                workers=1,
                slots_small=cfg.ring_slots_small,
                slots_large=cfg.ring_slots_large,
                large_rows=cfg.max_batch,
            )
            placeholder = reuseport_socket(cfg.host, cfg.port)
            child_cfg = dataclasses.replace(
                cfg, port=placeholder.getsockname()[1]
            )
            procs = start_frontends(child_cfg, ring, prep_path)
            service = RingService(
                routed, ring,
                max_group=cfg.max_group,
                max_inflight=cfg.max_inflight,
                threads=cfg.max_workers,
            )
            service.start()
            ring.set_ready(True)
            try:
                _wait_port(child_cfg.port)
                proc = subprocess.run(
                    [sys.executable, "-c", _BROWNOUT_CLIENT,
                     str(child_cfg.port), str(concurrency),
                     str(duration_s), str(backoff_s)],
                    input=body, stdout=subprocess.PIPE, timeout=600,
                )
                if proc.returncode != 0:
                    raise RuntimeError("tierroute burst client failed")
                counts = json.loads(
                    proc.stdout.decode().strip().splitlines()[-1]
                )
                counts["demotions"] = int(ring.tier_demote.sum())
                counts["brownout_demotions"] = int(
                    ring.brownout_demote.sum()
                )
                arms[arm] = counts
            finally:
                ring.set_draining()
                ring.set_ready(False)
                for p in procs:
                    if p.is_alive() and p.pid:
                        os.kill(p.pid, 15)
                for p in procs:
                    p.join(timeout=15)
                    if p.is_alive():
                        p.terminate()
                        p.join(timeout=5)
                service.stop()
                placeholder.close()
                ring.close()

    for arm, counts in arms.items():
        wall = max(counts.get("wall_s", 0.0), 1e-6)
        arms[arm]["goodput_req_per_s"] = round(counts["ok"] / wall, 1)
        out[f"brownout_{arm}_ok"] = counts["ok"]
        out[f"brownout_{arm}_shed"] = counts["shed"]
        out[f"brownout_{arm}_goodput_req_per_s"] = arms[arm][
            "goodput_req_per_s"
        ]
    out["brownout_demotions"] = arms["on"]["brownout_demotions"]
    off_goodput = arms["off"]["goodput_req_per_s"]
    if off_goodput:
        out["brownout_goodput_gain_pct"] = round(
            100.0
            * (arms["on"]["goodput_req_per_s"] - off_goodput)
            / off_goodput,
            1,
        )
    return out


def _tenancy_stage(engine, bundle, record) -> dict:
    """Multi-tenant multiplexing evidence (mlops_tpu/tenancy/, ISSUE 12)
    on an in-process 2-worker plane serving TWO tenants from one engine
    process:

    - ``tenants_shared_exec_count`` — the cold tenant's engine ADOPTS
      the warmed engine's compiled entries (the registry's
      architecture-twin dedupe, `InferenceEngine.adopt_executables`):
      N tenants at one architecture pay ONE warmup;
    - ``tenant_req_per_s_hot`` / ``tenant_req_per_s_cold`` — per-tenant
      goodput while the hot tenant floods at 10 connections;
    - ``starvation_cold_p99_ratio`` — the headline fairness number: the
      cold tenant's sequential p99 under the hot flood over its solo
      p99 (the weighted max-min floors must keep it near 1; the ISSUE
      acceptance bound is 2.0);
    - ``tenant_quota_shed_hot`` — admissions the hot tenant lost to ITS
      OWN quota during the flood (the fairness mechanism, observed).
    """
    import dataclasses
    import socket
    import tempfile
    import threading

    from mlops_tpu.config import ServeConfig
    from mlops_tpu.serve.engine import InferenceEngine
    from mlops_tpu.serve.frontend import reuseport_socket, start_frontends
    from mlops_tpu.serve.ipc import RequestRing, RingService
    from mlops_tpu.tenancy import TenancyConfig, TenantSpec

    twin = InferenceEngine(
        bundle,
        buckets=tuple(engine.buckets),
        enable_grouping=engine.supports_grouping,
    )
    # The sharing decision is MEASURED, not assumed: the twin adopts
    # only if the registry's own dedupe predicate matches — if
    # _arch_key regresses so architecture twins stop matching, this
    # stage fails loudly (tenancy_error) instead of emitting a
    # hardcoded sharing "proof".
    from mlops_tpu.tenancy.registry import _arch_key

    if _arch_key(twin) != _arch_key(engine):
        raise RuntimeError(
            "architecture twins no longer share: _arch_key mismatch"
        )
    twin.adopt_executables(engine)
    out: dict = {"tenants_shared_exec_count": 1}

    body = json.dumps([record]).encode()

    def payload_for(tenant: str) -> bytes:
        return (
            "POST /predict HTTP/1.1\r\nhost: bench\r\n"
            "content-type: application/json\r\n"
            f"x-tenant: {tenant}\r\n"
            f"content-length: {len(body)}\r\nconnection: close\r\n\r\n"
        ).encode() + body

    hot_payload, cold_payload = payload_for("hot"), payload_for("cold")
    fleet = TenancyConfig(
        tenants=(
            TenantSpec("hot", "unused", weight=1.0),
            TenantSpec("cold", "unused", weight=1.0),
        ),
        default_tenant="hot",
    )
    cfg = ServeConfig(
        host="127.0.0.1", port=0, workers=2, ring_slots_small=16
    ).validate()
    ring = RequestRing(
        workers=2,
        slots_small=cfg.ring_slots_small,
        slots_large=cfg.ring_slots_large,
        large_rows=cfg.max_batch,
        tenant_names=fleet.names,
    )
    clock = time.perf_counter
    with tempfile.TemporaryDirectory() as td:
        prep_path = os.path.join(td, "preprocess.npz")
        bundle.preprocessor.save(prep_path)
        placeholder = reuseport_socket(cfg.host, cfg.port)
        child_cfg = dataclasses.replace(
            cfg, port=placeholder.getsockname()[1]
        )
        procs = start_frontends(
            child_cfg, ring, [prep_path, prep_path], None, fleet
        )
        service = RingService(
            engine, ring,
            max_group=cfg.max_group,
            max_inflight=cfg.max_inflight,
            threads=cfg.max_workers,
            engines=[engine, twin],
        )
        service.start()
        ring.set_ready(True)
        try:
            _wait_port(child_cfg.port)
            port = child_cfg.port

            def exchange(payload: bytes) -> int:
                with socket.create_connection(
                    ("127.0.0.1", port), timeout=60
                ) as sock:
                    sock.sendall(payload)
                    data = b""
                    while True:
                        chunk = sock.recv(65536)
                        if not chunk:
                            break
                        data += chunk
                parts = data.split(b" ")
                if len(parts) < 2 or not parts[1].isdigit():
                    raise OSError("short/torn HTTP response")
                return int(parts[1])

            def cold_pass(n: int = 120) -> list[float]:
                # One torn/short response (most likely mid-flood, when
                # the contended pass matters most) drops that sample,
                # never the whole stage's keys — same tolerance as the
                # hammer threads.
                lat: list[float] = []
                for _ in range(n):
                    t0 = clock()
                    try:
                        status = exchange(cold_payload)
                    except OSError:
                        continue
                    if status == 200:
                        lat.append((clock() - t0) * 1e3)
                return lat

            for _ in range(10):  # connection/route warmup, both tenants
                for p in (hot_payload, cold_payload):
                    try:
                        exchange(p)
                    except OSError:
                        pass
            solo = sorted(cold_pass())
            if not solo:
                raise RuntimeError("cold tenant solo pass served nothing")
            solo_p99 = _percentile(solo, 99)

            stop = threading.Event()
            lock = threading.Lock()
            hot_ok = [0]

            def hammer() -> None:
                while not stop.is_set():
                    try:
                        status = exchange(hot_payload)
                    except OSError:
                        continue
                    if status == 200:
                        with lock:
                            hot_ok[0] += 1

            hammers = [
                threading.Thread(target=hammer, daemon=True)
                for _ in range(10)
            ]
            t_flood = clock()
            for t in hammers:
                t.start()
            time.sleep(0.5)  # the flood is established
            t0 = clock()
            contended = sorted(cold_pass())
            cold_wall_s = clock() - t0
            stop.set()
            for t in hammers:
                t.join(timeout=30)
            flood_wall_s = clock() - t_flood
            if not contended:
                raise RuntimeError("cold tenant starved to zero 200s")
            contended_p99 = _percentile(contended, 99)
            out["tenant_req_per_s_hot"] = round(
                hot_ok[0] / flood_wall_s, 1
            )
            out["tenant_req_per_s_cold"] = round(
                len(contended) / cold_wall_s, 1
            )
            out["tenant_cold_solo_p99_ms"] = round(solo_p99, 3)
            out["tenant_cold_contended_p99_ms"] = round(contended_p99, 3)
            out["starvation_cold_p99_ratio"] = round(
                contended_p99 / max(solo_p99, 1e-9), 2
            )
            out["tenant_quota_shed_hot"] = int(ring.quota_shed[:, 0].sum())
        finally:
            ring.set_draining()
            ring.set_ready(False)
            for proc in procs:
                if proc.is_alive() and proc.pid:
                    os.kill(proc.pid, 15)
            for proc in procs:
                proc.join(timeout=15)
                if proc.is_alive():
                    proc.terminate()
                    proc.join(timeout=5)
            service.stop()
            placeholder.close()
            ring.close()
    return out


def _replica_stage() -> dict:
    """Engine-replica-set scaling evidence (mlops_tpu/replicaset/,
    ISSUE 13): grouped req/s through the REAL ring + router + E REAL
    `RingService` consumers at E ∈ {1, 2, 4} simulated devices, all
    in-process.

    Device time is a simulated constant-latency round trip
    (``replica_sim_device_ms`` — the flat transport RTT the remote-chip
    path measures at ~70-90 ms, scaled down so the stage finishes in
    seconds): data-parallel replicas hide exactly that wait behind each
    other, which a single-core CI box could never demonstrate with real
    compute (one core runs one matmul at a time no matter how many
    processes ask — on TPU hardware the replicas' device time is
    genuinely parallel). Host-side work — descriptor queues, routing,
    coalescing, scatter, slab writes, doorbells — is all real and all
    inside the measurement. ``XLA_FLAGS=--xla_force_host_platform_
    device_count=E`` is the companion knob for runs wanting E visible
    jax devices; the sim itself is jax-free.

    Keys: ``replica_req_per_s_e{1,2,4}``, the headline
    ``replica_scaling_efficiency`` (= e4 / (4 * e1); acceptance floor
    0.75), per-replica goodput/depth splits from the E=4 run, and a
    zero ``replica_wrong_responses`` correctness pin (every simulated
    response is input-checked)."""
    import asyncio

    from mlops_tpu.replicaset.sim import build_sim_plane, drive_grouped_load

    device_ms = 20.0
    rates: dict[int, float] = {}
    out: dict = {"replica_sim_device_ms": device_ms}
    wrong = 0
    for e in (1, 2, 4):
        plane = build_sim_plane(
            replicas=e,
            device_ms=device_ms,
            slots_small=192,
            max_group=8,
            max_inflight=2,
        )
        try:
            # Warm pass (router sticky state, pool threads, free lists),
            # then the measured window.
            asyncio.run(
                drive_grouped_load(plane, duration_s=0.5, concurrency=128)
            )
            measured = asyncio.run(
                drive_grouped_load(plane, duration_s=2.0, concurrency=128)
            )
        finally:
            plane.stop()
        rates[e] = measured["req_per_s"]
        wrong += measured["wrong"]
        out[f"replica_req_per_s_e{e}"] = measured["req_per_s"]
        if e == 4:
            for r, rows in enumerate(measured["per_replica_rows"]):
                out[f"replica_rows_r{r}_e4"] = rows
            for r, depth in enumerate(measured["per_replica_peak_depth"]):
                out[f"replica_ring_depth_peak_r{r}_e4"] = depth
    out["replica_wrong_responses"] = wrong
    out["replica_scaling_efficiency_e2"] = round(
        rates[2] / max(2 * rates[1], 1e-9), 3
    )
    out["replica_scaling_efficiency"] = round(
        rates[4] / max(4 * rates[1], 1e-9), 3
    )
    return out


def _respawn_stage(bundle_dir: str, record) -> dict:
    """Survivable-engine evidence (ISSUE 11): boot the REAL 2-worker
    plane as a subprocess, hammer batch-1 requests carrying a generous
    deadline budget, SIGKILL the ENGINE process mid-run, and measure the
    brownout. ``engine_respawn_gap_ms`` is the headline: p99 latency of
    the PARKED requests (in flight or admitted during the outage,
    answered 200 by the respawned engine's replay) — what a client
    actually experiences across an engine death. The plane serves from a
    dedicated AOT cache dir so the respawn warm-starts by deserializing
    (the deployment-shape fast path, not a cold recompile)."""
    import re
    import signal
    import socket
    import subprocess
    import tempfile
    import threading

    repo = os.path.dirname(os.path.abspath(__file__))
    body = json.dumps([record]).encode()
    head = (
        "POST /predict HTTP/1.1\r\nhost: bench\r\n"
        "content-type: application/json\r\n"
        "x-request-deadline-ms: 90000\r\n"
        f"content-length: {len(body)}\r\nconnection: close\r\n\r\n"
    ).encode() + body

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def exchange(payload: bytes, timeout: float = 120.0) -> int:
        with socket.create_connection(
            ("127.0.0.1", port), timeout=timeout
        ) as sock:
            sock.settimeout(timeout)
            sock.sendall(payload)
            data = b""
            while True:
                chunk = sock.recv(65536)
                if not chunk:
                    break
                data += chunk
        parts = data.split(b" ")
        if len(parts) < 2 or not parts[1].isdigit():
            # A connection severed pre-status (brownout churn, drain):
            # surface as the OSError class every caller already retries.
            raise OSError("short/torn HTTP response")
        return int(parts[1])

    def ready() -> bool:
        try:
            return (
                exchange(
                    b"GET /healthz/ready HTTP/1.1\r\nhost: b\r\n"
                    b"connection: close\r\n\r\n",
                    timeout=5.0,
                )
                == 200
            )
        except OSError:
            return False

    with tempfile.TemporaryDirectory() as td:
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "mlops_tpu", "serve", "--workers",
                "2", "serve.host=127.0.0.1", f"serve.port={port}",
                f"serve.model_directory={bundle_dir}",
                "serve.warmup_batch_sizes=1,8", "serve.max_batch=8",
                "serve.request_timeout_s=120",
                f"cache.dir={os.path.join(td, 'cache')}",
                "serve.drain_deadline_s=8",
                "serve.zygote_join_deadline_s=10",
                "serve.engine_zygote_join_s=16",
            ],
            cwd=repo, env={**os.environ, "JAX_PLATFORMS": "cpu"},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        log_lines: list[str] = []
        pump = threading.Thread(
            target=lambda: log_lines.extend(
                iter(proc.stdout.readline, "")
            ),
            daemon=True,
        )
        pump.start()
        results: list[tuple[float, float, int]] = []  # (start, wall_s, st)
        lock = threading.Lock()
        stop = threading.Event()
        clock = time.perf_counter
        try:
            deadline = time.time() + 600
            while time.time() < deadline and not ready():
                if proc.poll() is not None:
                    raise RuntimeError(
                        "respawn-stage plane died before readiness: "
                        + "\n".join(log_lines[-25:])
                    )
                time.sleep(0.5)
            if not ready():
                raise RuntimeError("respawn-stage plane never ready")
            engine_line = next(
                line for line in log_lines if "engine pid" in line
            )
            engine_pid = int(
                re.search(r"engine pid (\d+)", engine_line).group(1)
            )

            def hammer() -> None:
                while not stop.is_set():
                    t0 = clock()
                    try:
                        status = exchange(head)
                    except OSError:
                        continue
                    with lock:
                        results.append((t0, clock() - t0, status))

            threads = [
                threading.Thread(target=hammer) for _ in range(4)
            ]
            for t in threads:
                t.start()
            time.sleep(3.0)  # steady state
            kill_t = clock()
            os.kill(engine_pid, signal.SIGKILL)
            # Recovery = the first 200 that STARTED after the kill has
            # completed (the respawned engine is serving fresh traffic).
            recover_t = None
            deadline = time.time() + 300
            while time.time() < deadline and recover_t is None:
                time.sleep(0.25)
                with lock:
                    done = [
                        (t0, wall) for t0, wall, st in results
                        if st == 200 and t0 > kill_t
                    ]
                if done:
                    recover_t = min(t0 + wall for t0, wall in done)
            if recover_t is None:
                raise RuntimeError("plane never recovered after the kill")
            time.sleep(2.0)  # post-recovery tail for the latency picture
            stop.set()
            for t in threads:
                t.join(timeout=60)
        finally:
            stop.set()
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)
    with lock:
        snapshot = list(results)
    statuses: dict[str, int] = {}
    for _, _, st in snapshot:
        statuses[str(st)] = statuses.get(str(st), 0) + 1
    illegal = [st for st in statuses if st not in ("200", "503", "504")]
    if illegal:
        raise RuntimeError(
            f"statuses outside the brownout contract: {statuses}"
        )
    # Parked = answered 200 AND the request's lifetime overlapped the
    # outage window [kill, recovery].
    parked = sorted(
        wall * 1e3
        for t0, wall, st in snapshot
        if st == 200 and t0 <= recover_t and t0 + wall >= kill_t
    )
    outage_ms = (recover_t - kill_t) * 1e3
    gap_ms = _percentile(parked, 99) if parked else outage_ms
    return {
        "engine_respawn_gap_ms": round(gap_ms, 1),
        "engine_respawn_outage_ms": round(outage_ms, 1),
        "engine_respawn_parked": len(parked),
        "engine_respawn_statuses": statuses,
    }


def _lifecycle_stage(engine, bundle, record) -> dict:
    """Closed-loop lifecycle evidence (mlops_tpu/lifecycle/) on a
    synthetic drift-injected trace:

    - ``retrain_trigger_to_promote_s`` — wall time from the drift trigger
      firing to the candidate hot-swapping in (retrain + shadow warm +
      mirrored gate evidence + promotion),
    - ``swap_downtime_ms`` — p99 request latency in the window bracketing
      the live promotion minus the pre-loop baseline p99 (the zero-
      downtime claim, measured under concurrent traffic),
    - ``shadow_mirror_overhead_pct`` — hot-path throughput cost of the
      lifecycle tee + mirroring while a candidate is shadowing.

    Runs LAST: promotion swaps the live engine's bundle (generation 2),
    so no other stage may measure after it."""
    import tempfile
    import time as _time

    from mlops_tpu.config import Config as _Config
    from mlops_tpu.data import generate_synthetic, write_csv_columns
    from mlops_tpu.lifecycle import LifecycleController
    from mlops_tpu.schema import SCHEMA, records_to_columns

    pc = _time.perf_counter
    if not getattr(engine, "monitor_accumulating", False):
        # sklearn/tree flavors have no device monitor accumulator, so the
        # drift trigger can never fire — fail the stage instantly instead
        # of spinning the 300 s drive loop to the same conclusion.
        return {
            "lifecycle_error": "non-accumulating engine (sklearn flavor): "
            "the loop requires the device monitor accumulator"
        }
    out: dict = {}
    prep = bundle.preprocessor
    del records_to_columns, record  # the trace is synthetic drifted traffic
    columns, labels = generate_synthetic(2000, seed=11)
    drift_cols = {k: list(v) for k, v in columns.items()}
    for feat in SCHEMA.numeric:
        drift_cols[feat.name] = [v * 10.0 for v in drift_cols[feat.name]]
    ds_drift = prep.encode(drift_cols)
    # The drifted trace request: 8 rows (a decisive K-S window per
    # dispatch — batch-1 K-S is noisy) reused for baseline, hammer, and
    # mirror measurements so every latency number describes ONE shape.
    dcat, dnum = ds_drift.cat_ids[:8], ds_drift.numeric[:8]

    # Baseline (no controller attached): p99 + throughput on the trace
    # shape.
    lat = []
    for _ in range(100):
        t0 = pc()
        engine.predict_arrays(dcat, dnum)
        lat.append((pc() - t0) * 1e3)
    lat.sort()
    base_p99 = _percentile(lat, 99)
    reps = 100
    t0 = pc()
    for _ in range(reps):
        engine.predict_arrays(dcat, dnum)
    base_rate = reps / (pc() - t0)

    with tempfile.TemporaryDirectory() as td:
        write_csv_columns(f"{td}/labeled.csv", drift_cols, labels)
        config = _Config()
        lc = config.lifecycle
        lc.enabled = True
        lc.dir = f"{td}/state"
        lc.labeled_path = f"{td}/labeled.csv"
        lc.retrain_steps = int(os.environ.get("BENCH_LIFECYCLE_STEPS", "40"))
        lc.min_labeled_rows = 500
        lc.min_window_rows = 64
        lc.hysteresis_windows = 2
        lc.cooldown_s = 0.0
        lc.mirror_fraction = 1.0
        lc.shadow_min_mirrors = 8
        lc.max_ece = 0.5  # the bench grades speed; quality gates stay sane
        lc.max_p99_ratio = 10.0
        ctrl = LifecycleController(engine, config)
        try:
            samples: list[tuple[float, float]] = []
            stop = _threading.Event()
            pause = _threading.Event()

            def hammer() -> None:
                # The live drifted trace: drives the trigger windows, the
                # mirror stream, and the per-request latency record the
                # swap-downtime key reads. Pausable so the mirror-overhead
                # rate is measured single-threaded like its baseline (the
                # key must isolate the tee cost, not GIL contention with
                # this thread).
                while not stop.is_set():
                    if pause.is_set():
                        _time.sleep(0.005)
                        continue
                    h0 = pc()
                    engine.predict_arrays(dcat, dnum)
                    samples.append((h0, (pc() - h0) * 1e3))

            thread = _threading.Thread(target=hammer, daemon=True)
            thread.start()
            triggered_at = promoted_at = None
            mirror_rate = 0.0
            deadline = pc() + 300.0
            status: dict = {}
            while pc() < deadline:
                tick_start = pc()
                status = ctrl.run_once()
                if triggered_at is None and status["drift_triggers"]:
                    # The run_once that fires the trigger also runs the
                    # retrain + shadow warm INLINE before returning —
                    # stamp the tick's START so the key covers them (a
                    # post-call stamp would exclude the retrain wall
                    # entirely).
                    triggered_at = tick_start
                if status["state"] == "shadowing" and not mirror_rate:
                    # Tee active, candidate shadowing: the hot-path
                    # overhead sample, single-threaded like its baseline
                    # (mirror scoring itself runs on the controller
                    # thread between ticks, off the request path).
                    pause.set()
                    _time.sleep(0.02)  # drain the in-flight hammer call
                    m0 = pc()
                    for _ in range(reps):
                        engine.predict_arrays(dcat, dnum)
                    mirror_rate = reps / (pc() - m0)
                    pause.clear()
                if status["promotions"]["promoted"]:
                    promoted_at = pc()
                    break
                _time.sleep(0.25)  # let the hammer fill the next window
            stop.set()
            thread.join(timeout=30)
            if promoted_at is None:
                raise RuntimeError(
                    f"loop never promoted: {status['last_error'] or status}"
                )
            out["retrain_trigger_to_promote_s"] = round(
                promoted_at - triggered_at, 2
            )
            out["bundle_generation"] = int(engine.bundle_generation)
            # p99 over the window bracketing the swap (the promotion
            # happened inside the final run_once) vs the quiet baseline.
            window = sorted(
                ms for t, ms in samples if promoted_at - 1.0 <= t
            ) or sorted(ms for _, ms in samples)
            out["swap_downtime_ms"] = round(
                _percentile(window, 99) - base_p99, 3
            )
            if mirror_rate:
                out["shadow_mirror_overhead_pct"] = round(
                    max(base_rate / mirror_rate - 1.0, 0.0) * 100.0, 2
                )
            report = status["last_report"] or {}
            for key in ("auc_delta", "warm_mode", "warm_s", "mirrors"):
                if key in report:
                    out[f"lifecycle_{key}"] = report[key]
        finally:
            ctrl.stop()  # detaches the engine tee, snapshots the reservoir
    return out


def _analysis_stage() -> dict:
    """Wall time of the full static gate (Layers 1+3+4+5 plus the
    suppression audit; ``--no-trace`` keeps device work out of it). The
    analyzer is framework code too: a Layer-4 pass that quietly goes
    quadratic on the project graph is a CI-latency regression, and this
    key makes it visible in the BENCH_* trajectory like any other
    number. The strict run's per-layer timings line is parsed into
    ``analysis_<layer>_s`` satellites, so a single layer regressing
    (layer5's call-graph fixpoint, the audit's project re-runs) is
    attributable instead of smeared across the total."""
    import re as _re
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    start = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "mlops_tpu", "analyze", "--no-trace",
         "--strict", "--concurrency", "--contracts", "--async",
         "--fail-stale", os.path.join(repo, "mlops_tpu")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=600,
        cwd=repo,
    )
    out = {"analysis_wall_s": round(time.perf_counter() - start, 2)}
    stdout = proc.stdout.decode(errors="replace")
    timings = _re.search(r"layer timings: (.+)", stdout)
    if timings:
        for name, spent in _re.findall(
            r"(\w+) ([0-9.]+)s", timings.group(1)
        ):
            out[f"analysis_{name}_s"] = float(spent)
    if proc.returncode != 0:
        out["analysis_gate_error"] = (
            f"exit {proc.returncode}: " + stdout.strip()[-300:]
        )
    return out


def _wait_port(port: int, timeout: float = 30.0) -> None:
    import socket as _socket

    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        try:
            with _socket.create_connection(("127.0.0.1", port), timeout=1):
                return
        except OSError:
            time.sleep(0.05)
    raise TimeoutError(f"no front end accepting on :{port}")


def _prune_bench_runs(run_root: str, keep: int) -> None:
    """Every invocation leaves one runs/bench/<name> dir; keep the newest
    ``keep`` so repeated benches don't grow the workspace forever."""
    import shutil

    try:
        # Newest-by-mtime, NOT by name: names lead with the model family,
        # so a lexical sort would rank families alphabetically and could
        # prune a concurrently-RUNNING bench's dir (active dirs have
        # recent mtimes and survive an mtime sort).
        paths = [
            os.path.join(run_root, d)
            for d in os.listdir(run_root)
            if d.startswith("bench")
        ]
        paths.sort(key=os.path.getmtime, reverse=True)
        for stale in paths[keep:]:
            shutil.rmtree(stale, ignore_errors=True)
    except OSError:
        pass


def _error_line(message: str) -> str:
    """The one-JSON-line contract's failure shape — single definition for
    the crash handler and the wall watchdog."""
    return json.dumps(
        {
            "metric": "inference_p50_latency_ms",
            "value": None,
            "unit": "ms",
            "vs_baseline": 0.0,
            "error": message,
        }
    )


def _arm_wall_watchdog(timeout_s: int):
    """The init probe can't protect against a MID-RUN tunnel stall (backend
    healthy at start, a later dispatch blocks forever in C++; observed
    live — a ~40 min dead hang). On expiry, a TPU-path run RE-EXECS under
    ``JAX_PLATFORMS=cpu`` (exec replaces the image, reaping the stalled
    runtime threads) so the driver still gets real measured numbers; a run
    that was already forced to a backend prints the error line and
    hard-exits instead (``os._exit`` — a stalled runtime thread would
    ignore a normal exit). Returns the timer; main() cancels it after the
    success line so a run finishing near the deadline can't be clobbered."""

    def expire():
        if _BENCH_DONE.is_set():
            return  # success line already printed; nothing to rescue
        if _on_tpu_path():
            _reexec_on_cpu(f"device stalled mid-run past {timeout_s}s")
        print(
            _error_line(
                f"bench wall timeout after {timeout_s}s (mid-run device stall)"
            ),
            flush=True,
        )
        os._exit(1)

    timer = _threading.Timer(timeout_s, expire)
    timer.daemon = True
    timer.start()
    return timer


def main() -> None:
    # Honor an explicit JAX_PLATFORMS env (the container bootstrap otherwise
    # pins the TPU backend, hanging CPU-only runs on the tunnel dial).
    _ensure_healthy_backend(int(os.environ.get("BENCH_TPU_TIMEOUT_S", "150")))
    watchdog = _arm_wall_watchdog(
        # Per-attempt budget. A full flagship bench through the remote-chip
        # tunnel measures ~15 min when healthy (train ~8.5 min + stages;
        # see the stderr breadcrumbs), so 1500 s left no headroom for a
        # slow-but-alive tunnel; the CPU re-exec arms a fresh budget and
        # finishes in ~8 min regardless.
        int(os.environ.get("BENCH_WALL_TIMEOUT_S", "2100"))
    )

    from mlops_tpu.commands import _honor_jax_platforms_env

    _honor_jax_platforms_env()

    import jax

    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.config import Config, ModelConfig, TrainConfig
    from mlops_tpu.schema import LoanApplicant
    from mlops_tpu.serve.engine import InferenceEngine
    from mlops_tpu.train.pipeline import run_training

    try:
        device = jax.devices()[0]
    except Exception:
        # The init probe can pass and the plugin registration still fail
        # moments later (observed on a flapping tunnel: "Backend 'axon' is
        # not in the list of known backends"). On the TPU path, fall back
        # to measured CPU numbers; a non-TPU JAX_PLATFORMS is the user's
        # explicit choice, so respect it and let the crash handler report
        # (the forced-failure contract test depends on this).
        if not _on_tpu_path():
            raise
        _reexec_on_cpu("device acquisition failed")
    family = os.environ.get("BENCH_MODEL", "mlp")
    # Flagship = 8-member vmapped deep ensemble (models/ensemble.py): beats
    # the sklearn GBM floor on AUC (0.8056 vs 0.8048) at ~0.6 ms extra CPU
    # p50. BENCH_ENSEMBLE=1 measures the single model.
    ensemble = int(os.environ.get("BENCH_ENSEMBLE", "8")) if family == "mlp" else 1

    config = Config()
    config.data.rows = 50_000
    config.model = ModelConfig(family=family, ensemble_size=ensemble)
    config.train = TrainConfig(
        batch_size=1024, steps=600, eval_every=600, warmup_steps=60,
        # Quant tier (ISSUE 17): distill + quantize + gate the int8/bf16
        # student at packaging time so the bulk stage can measure it.
        distill_quant=True,
    )
    config.registry.run_root = "runs/bench"
    _note(f"backend up, device={device}; training {family} ens={ensemble}")
    t_train = time.perf_counter()
    # Fresh run dir per invocation (ns + pid so concurrent same-second
    # benches can't share): a reused dir either resumes from its own
    # checkpoints (train_wall_s would measure a restore, not training)
    # or — across families — warns about a mismatched param tree before
    # retraining. Old bench run dirs are pruned to the newest few.
    _prune_bench_runs(config.registry.run_root, keep=5)
    result = run_training(
        config,
        register=False,
        run_name=f"bench-{family}-{time.time_ns()}-{os.getpid()}",
    )
    train_wall_s = time.perf_counter() - t_train
    bundle = load_bundle(result.bundle_dir)

    _note(f"training done in {train_wall_s:.1f}s; warming engine")
    engine = InferenceEngine(bundle, buckets=(1, 8, 64, 256, 4096, 16384))
    engine.warmup()

    record = LoanApplicant().model_dump()
    _note("warm; batch-1 stage")
    batch1 = _batch1_stage(engine, record)
    _note("monitor aggregate stage")
    monitor_stats = _monitor_stage(engine)
    _note("faults stage (armed-off overhead + degraded dispatch)")
    try:
        # Robustness evidence, guarded: chaos instrumentation must never
        # cost the run its headline numbers.
        faults_stats = _faults_stage(engine, record)
    except Exception as err:
        faults_stats = {"fault_stage_error": f"{type(err).__name__}: {err}"}
    _note("trace stage (tracewire overhead + shape goodput)")
    try:
        # Observability evidence, guarded like faults: tracing
        # instrumentation must never cost the run its headline numbers.
        faults_stats.update(_trace_stage(engine, record))
    except Exception as err:
        faults_stats["trace_stage_error"] = f"{type(err).__name__}: {err}"
    _note("slo stage (sloscope armed-vs-disarmed batch-1 overhead)")
    try:
        # sloscope evidence (ISSUE 14), guarded like faults/trace: the
        # health layer's instrumentation must never cost the run its
        # headline numbers.
        faults_stats.update(_slo_stage(engine, record))
    except Exception as err:
        faults_stats["slo_stage_error"] = f"{type(err).__name__}: {err}"
    _note("bulk stage")
    bulk = _bulk_stage(engine, bundle)
    _note("stream pipeline stage")
    try:
        # Guarded like the roofline extras: the streaming sweep is
        # evidence, never the reason a run loses its headline numbers.
        bulk.update(_stream_stage(bundle))
    except Exception as err:
        bulk["bulk_stream_error"] = f"{type(err).__name__}: {err}"
    _note("roofline stage")
    try:
        # Roofline extras are evidence, not the headline: a cost-analysis
        # or kernel quirk on a new backend must not turn a measured run
        # into an error line.
        roofline = _mfu_stage(bundle, bulk, device)
    except Exception as err:
        roofline = {"mfu_error": f"{type(err).__name__}: {err}"}
    _note("cold/warm start stage")
    try:
        # Guarded: deploy-path evidence, never the reason a run loses its
        # headline numbers. (The ~54 s warmup this stage makes visible was
        # previously invisible in BENCH_*.json.)
        coldstart = _coldstart_stage(result.bundle_dir)
    except Exception as err:
        coldstart = {"engine_cold_start_error": f"{type(err).__name__}: {err}"}
    _note("engine grouped stage")
    engine_stats = _engine_stage(engine, record)
    _note("batcher admission-mode stage (windowed vs continuous)")
    try:
        # Continuous micro-batching evidence (ISSUE 17), guarded like the
        # other plane stages.
        engine_stats.update(_batcher_mode_stage(engine, record))
    except Exception as err:
        engine_stats["batcher_mode_error"] = f"{type(err).__name__}: {err}"
    _note("autotune stage (gridtuner: measured regrid gain + downtime)")
    try:
        # Gridtuner evidence (ISSUE 18), guarded like the other plane
        # stages. Runs on its own engine so the shared bench engine's
        # grid is never disturbed.
        engine_stats.update(_autotune_stage(bundle, record))
    except Exception as err:
        engine_stats["autotune_stage_error"] = f"{type(err).__name__}: {err}"
    _note("http stage")
    http = {**engine_stats, **_http_stage(engine, record)}
    _note("http multi-worker stage")
    try:
        # Multi-worker evidence (SO_REUSEPORT front ends + shm ring),
        # guarded: a fork/port quirk on an exotic host must not cost the
        # run its headline numbers.
        http.update(_http_multi_stage(engine, bundle, record, http))
    except Exception as err:
        http["http_multi_error"] = f"{type(err).__name__}: {err}"
    _note("tierroute stage (per-class routing + brownout-vs-shed A/B)")
    try:
        # Tiered SLO serving evidence (ISSUE 19), guarded like the
        # other plane stages.
        http.update(_tierroute_stage(bundle, record))
    except Exception as err:
        http["tierroute_error"] = f"{type(err).__name__}: {err}"
    _note("tenancy stage (2-tenant fleet, shared exec, 10x hot flood)")
    try:
        # Multi-tenant multiplexing evidence (ISSUE 12), guarded like
        # the other plane stages.
        http.update(_tenancy_stage(engine, bundle, record))
    except Exception as err:
        http["tenancy_error"] = f"{type(err).__name__}: {err}"
    _note("replica stage (E-replica fan-out scaling, simulated devices)")
    try:
        # Engine-replica-set evidence (ISSUE 13), guarded like the
        # other plane stages.
        http.update(_replica_stage())
    except Exception as err:
        http["replica_stage_error"] = f"{type(err).__name__}: {err}"
    _note("engine respawn stage (kill -9 the engine under load)")
    try:
        # Survivable-engine evidence (ISSUE 11), guarded like the other
        # plane stages: a fork/port quirk must not cost the run its
        # headline numbers.
        http.update(_respawn_stage(result.bundle_dir, record))
    except Exception as err:
        http["engine_respawn_error"] = f"{type(err).__name__}: {err}"
    _note("lifecycle stage (drift-inject -> retrain -> hot swap)")
    try:
        # LAST stage by contract: the gated promotion swaps the live
        # engine's bundle. Guarded like every satellite — the closed-loop
        # evidence must never cost the run its headline numbers.
        lifecycle = _lifecycle_stage(engine, bundle, record)
    except Exception as err:
        lifecycle = {"lifecycle_error": f"{type(err).__name__}: {err}"}
    _note("static-analysis gate timing")
    try:
        analysis = _analysis_stage()
    except Exception as err:
        analysis = {"analysis_stage_error": f"{type(err).__name__}: {err}"}
    _note("stages complete")

    p50 = batch1["p50_ms"]
    _BENCH_DONE.set()  # from here on the watchdog must not interfere
    print(
        json.dumps(
            {
                "metric": "inference_p50_latency_ms",
                "value": round(p50, 4),
                "unit": "ms",
                "vs_baseline": round(5.0 / p50, 3),
                "p99_ms": round(batch1["p99_ms"], 4),
                "batch1_req_per_s": round(1e3 / p50, 1),
                "lock_wait_ms": batch1["lock_wait_ms"],
                "breakdown_ms": batch1["breakdown_ms"],
                **monitor_stats,
                **faults_stats,
                **bulk,
                **roofline,
                **coldstart,
                **http,
                **lifecycle,
                **analysis,
                "device": str(device),
                "model": family if ensemble == 1 else f"{family}-ens{ensemble}",
                # Training throughput for the bundle above (data gen +
                # encode + compile + scan windows): rows/s = steps×batch/wall.
                "train_wall_s": round(train_wall_s, 1),
                "train_rows_per_s": round(
                    config.train.steps * config.train.batch_size / train_wall_s, 1
                ),
                "model_auc": round(
                    result.train_result.metrics["validation_roc_auc_score"], 4
                ),
            }
        ),
        flush=True,
    )
    watchdog.cancel()  # best effort; _BENCH_DONE closes the fire-during-print race


if __name__ == "__main__":
    try:
        main()
    except BaseException as err:  # the one-JSON-line contract survives
        # crashes: emit a parseable line with the failure, then exit 1.
        print(_error_line(f"{type(err).__name__}: {err}"), flush=True)
        raise SystemExit(1)
