#!/bin/bash
# Round-5 TPU capture loop: probe the axon tunnel every ~3 min; on a
# healthy probe run the full flagship bench and keep the artifact if it
# really ran on TPU (not the CPU re-exec fallback). Stops on first TPU
# capture or after ~11h of attempts.
LOG=/root/repo/runs/bench/capture_r5.log
echo "$(date -Is) capture loop start (pid $$)" >> "$LOG"
for i in $(seq 1 220); do
  if timeout -k 10 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    ts=$(date +%m%d_%H%M%S)
    echo "$(date -Is) probe $i OK -> bench attempt $ts" >> "$LOG"
    out=/root/repo/runs/bench/tpu_r5_${ts}.json
    err=/root/repo/runs/bench/tpu_r5_${ts}.log
    BENCH_TPU_RETRIES=2 timeout -k 30 2400 python /root/repo/bench.py > "$out" 2> "$err"
    rc=$?
    if grep -q '"device": "TPU' "$out" 2>/dev/null; then
      echo "$(date -Is) TPU BENCH CAPTURED rc=$rc -> $out" >> "$LOG"
      exit 0
    fi
    echo "$(date -Is) bench rc=$rc but device not TPU (kept $out)" >> "$LOG"
  else
    echo "$(date -Is) probe $i dead" >> "$LOG"
  fi
  sleep 180
done
echo "$(date -Is) capture loop exhausted" >> "$LOG"
exit 1
