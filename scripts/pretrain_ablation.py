"""Does pretraining help? The scripted comparison behind BASELINE config 5.

Same fine-tune budget, same labeled data, same seeds — the ONLY difference
is whether the BERT trunk starts from masked-feature pretraining
(`train/pretrain.py`) or fresh init. Run in the label-scarce regime where
self-supervision earns its keep: plenty of unlabeled rows for the MLM
stage, a small labeled subset for fine-tuning (the reference's setting is
label-rich supervised sklearn, which has no pretrain stage at all —
`01-train-model.ipynb`; this capability is additive).

Reproduce:
    JAX_PLATFORMS=cpu python scripts/pretrain_ablation.py
Prints one JSON line:
    {"auc_scratch": ..., "auc_pretrained": ..., "auc_delta": ...,
     "seeds": N, ...}
with per-seed AUCs; auc_delta > 0 means pretraining helped. The headline
numbers land in BASELINE.md ("Round-4 additions").

Knobs (env): ABLATION_UNLABELED_ROWS (default 40000), ABLATION_LABELED_ROWS
(default 1500), ABLATION_SEEDS (default 3), ABLATION_PRETRAIN_STEPS (600),
ABLATION_FINETUNE_STEPS (300).
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

from mlops_tpu.commands import _honor_jax_platforms_env  # noqa: E402

# The container bootstrap force-sets jax_platforms="axon,cpu" (TPU tunnel)
# over the env var; re-assert JAX_PLATFORMS=cpu the way the CLI does.
_honor_jax_platforms_env()


def main() -> None:
    import jax

    from mlops_tpu.config import ModelConfig, TrainConfig
    from mlops_tpu.data import Preprocessor, generate_synthetic
    from mlops_tpu.models import build_model, init_params
    from mlops_tpu.train.loop import evaluate, fit
    from mlops_tpu.train.pretrain import fine_tune_params, pretrain_bert

    unlabeled_rows = int(os.environ.get("ABLATION_UNLABELED_ROWS", "40000"))
    labeled_rows = int(os.environ.get("ABLATION_LABELED_ROWS", "1500"))
    seeds = int(os.environ.get("ABLATION_SEEDS", "3"))
    pretrain_steps = int(os.environ.get("ABLATION_PRETRAIN_STEPS", "600"))
    finetune_steps = int(os.environ.get("ABLATION_FINETUNE_STEPS", "300"))

    model_config = ModelConfig(
        family="bert", token_dim=64, depth=2, heads=4, dropout=0.1
    )

    # One shared pool: unlabeled pretraining rows, a labeled fine-tune
    # subset, and a held-out eval split — all from the same generative
    # process. The preprocessor fits on the UNLABELED POOL ONLY (the
    # realistic order: stats exist before labels do, and holdout rows
    # must not leak into the standardization the eval runs under).
    columns, labels = generate_synthetic(unlabeled_rows + 8000, seed=100)
    prep = Preprocessor.fit(
        {k: v[:unlabeled_rows] for k, v in columns.items()}
    )
    ds = prep.encode(columns, labels)
    unlabeled = ds.slice(np.arange(unlabeled_rows))
    holdout = ds.slice(np.arange(unlabeled_rows + 4000, ds.n))

    pretrained = pretrain_bert(
        model_config,
        unlabeled,
        steps=pretrain_steps,
        batch_size=512,
        learning_rate=3e-3,
        seed=7,
    )

    tconfig = TrainConfig(
        batch_size=256,
        steps=finetune_steps,
        eval_every=finetune_steps,
        warmup_steps=finetune_steps // 10,
        learning_rate=1e-3,
    )

    scratch_aucs, pretrained_aucs = [], []
    for seed in range(seeds):
        rng = np.random.default_rng(200 + seed)
        idx = rng.choice(4000, labeled_rows, replace=False) + unlabeled_rows
        labeled = ds.slice(idx)
        run_config = TrainConfig(**{**tconfig.__dict__, "seed": seed})

        model = build_model(model_config)
        for use_pretrain, sink in ((False, scratch_aucs), (True, pretrained_aucs)):
            init_variables = None
            if use_pretrain:
                fresh = init_params(model, jax.random.PRNGKey(seed))
                init_variables = fine_tune_params(pretrained, fresh)
            result = fit(
                model,
                labeled,
                holdout,
                run_config,
                init_variables=init_variables,
            )
            auc = evaluate(model, result.params, holdout)[
                "validation_roc_auc_score"
            ]
            sink.append(float(auc))

    out = {
        "auc_scratch": round(float(np.mean(scratch_aucs)), 4),
        "auc_pretrained": round(float(np.mean(pretrained_aucs)), 4),
        "auc_delta": round(
            float(np.mean(pretrained_aucs) - np.mean(scratch_aucs)), 4
        ),
        "per_seed_scratch": [round(a, 4) for a in scratch_aucs],
        "per_seed_pretrained": [round(a, 4) for a in pretrained_aucs],
        "seeds": seeds,
        "unlabeled_rows": unlabeled_rows,
        "labeled_rows": labeled_rows,
        "pretrain_steps": pretrain_steps,
        "finetune_steps": finetune_steps,
    }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
