"""CI chaos smoke: the live serve plane under seeded fault injection.

The deployment-path proof for ISSUE 9 (faultline): train a tiny bundle,
launch the REAL `mlops-tpu serve --workers 2` plane with a seeded fault
plan armed through `MLOPS_TPU_FAULTS` (every process — supervisor,
engine, front ends — arms at import), and drive the failure scenarios
end to end:

1. engine stall  — a seeded delay fault on `serve.engine.dispatch*`:
   requests carrying `x-request-deadline-ms` answer the documented 504
   inside their budget; nothing hangs.
2. slow client   — a byte-dribbling request must not wedge concurrent
   traffic (and completes 200 itself).
3. overload      — a connection burst against a deliberately tiny ring:
   every response is in the contract set (sheds answer 503+Retry-After).
4. worker kill   — SIGKILL a front end mid-traffic: the supervisor
   respawns it and the plane keeps serving (slot quarantine drains).
5. ENGINE kill (ISSUE 11) — SIGKILL the engine process under live
   budgeted traffic: the supervisor forks a replacement that warm-starts
   from the AOT cache, re-attaches under a new incarnation, and replays
   the busy slots. Asserts: zero statuses outside {200, 503, 504} during
   the outage, every 504 inside its deadline budget, identical 200
   bodies across the respawn (replay bit-identity), recovery, and
   `engine_respawn_total >= 1` with MONOTONE counters across the respawn.
6. mid-write kills (subprocesses) — SIGKILL between tmp-write and rename
   on the compile-cache persist, the reservoir snapshot, and
   `utils.io.atomic_write`: no torn file ever lands.
7. cache corruption — seeded bit flips at `compilecache.read`: counted
   discard + recompile, correct outputs, self-healed store.
8. mid-regrid kill -9 (ISSUE 18) — SIGKILL a hot regrid between its
   warm phase and its swap, under a live serving hammer: the crash must
   leave nothing wedged — a fresh process over the same bundle + cache
   serves bit-identically, completes the regrid cleanly, and rolls back.

Global assertions: every /predict status is in {200, 413, 422, 503, 504},
at least one 504 was produced by the stall scenario, no request hangs
(every client call is deadline-bounded), /metrics counters are MONOTONE
across scrapes, and SIGTERM drains the plane cleanly (exit 0, no leaked
tasks) under the chaos-tuned drain knobs.

Run from the repo root: `python scripts/chaos_smoke.py` (CI pins
JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import textwrap
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RECORD = {"credit_limit": 12000, "age": 34}
ALLOWED_STATUSES = {200, 413, 422, 503, 504}

CHAOS_PLAN = """\
seed = 42

# Engine stall: seeded delays on the engine dispatch points. Probability
# is per-hit Bernoulli on a deterministic hash, so a fixed request count
# replays a fixed stall schedule.
[[fault]]
point = "serve.engine.dispatch*"
mode = "delay"
delay_s = 1.2
probability = 0.15
"""


def free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def get(url: str, timeout: float = 15.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def raw_predict(port, body: bytes, headers=None, timeout=20.0):
    """One blocking /predict exchange, deadline-bounded (a hang fails the
    smoke via the socket timeout, never via CI's job timeout)."""
    head = [
        "POST /predict HTTP/1.1", "host: chaos",
        "content-type: application/json",
        f"content-length: {len(body)}",
    ]
    for name, value in (headers or {}).items():
        head.append(f"{name}: {value}")
    head.append("connection: close")
    payload = ("\r\n".join(head) + "\r\n\r\n").encode() + body
    with socket.create_connection(("127.0.0.1", port), timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall(payload)
        data = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
    head_bytes, _, body_bytes = data.partition(b"\r\n\r\n")
    return int(head_bytes.split(b" ")[1]), head_bytes, body_bytes


def parse_counters(text: str) -> dict[str, float]:
    """Every `*_total` counter sample keyed by its full series name+labels
    — the monotonicity contract is per series."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("#") or "_total" not in line:
            continue
        name, _, value = line.rpartition(" ")
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


def run_subprocess_scenario(name: str, script: str, env=None, expect_kill=False):
    proc = subprocess.run(
        [sys.executable, "-c", script],
        env={**os.environ, "JAX_PLATFORMS": "cpu", **(env or {})},
        capture_output=True, text=True, timeout=600, cwd=REPO,
    )
    if expect_kill:
        assert proc.returncode == -signal.SIGKILL, (
            f"{name}: expected SIGKILL, got {proc.returncode}\n"
            f"{proc.stdout[-1000:]}\n{proc.stderr[-1000:]}"
        )
    else:
        assert proc.returncode == 0, (
            f"{name}: exit {proc.returncode}\n"
            f"{proc.stdout[-1000:]}\n{proc.stderr[-2000:]}"
        )
    print(f"# chaos-smoke: scenario OK — {name}", flush=True)
    return proc


# ------------------------------------------------- mid-write kill scripts
_RESERVOIR_KILL = """
import numpy as np
from mlops_tpu import faults
from mlops_tpu.lifecycle.retrain import SampleReservoir
from mlops_tpu.schema import SCHEMA
faults.arm(faults.FaultPlan.from_rules(
    [{"point": "lifecycle.reservoir.midwrite", "mode": "kill"}]))
res = SampleReservoir(16, {state!r})
res.add_batch(np.ones((4, SCHEMA.num_categorical), np.int32),
              np.ones((4, SCHEMA.num_numeric), np.float32))
res.save()
raise SystemExit("kill fault did not fire")
"""

_ATOMIC_KILL = """
from mlops_tpu import faults
from mlops_tpu.utils.io import atomic_write
atomic_write({target!r}, b"GOOD" * 1024)
faults.arm(faults.FaultPlan.from_rules(
    [{"point": "io.atomic_write.midwrite", "mode": "kill"}]))
atomic_write({target!r}, b"TORN" * 4096)
raise SystemExit("kill fault did not fire")
"""

_CACHE_KILL = """
import jax, jax.numpy as jnp
from mlops_tpu import faults
from mlops_tpu.compilecache.cache import (
    CacheJob, CompileCache, serialization_available)
if not serialization_available():
    print("NO-SERIALIZATION"); raise SystemExit(0)
faults.arm(faults.FaultPlan.from_rules(
    [{"point": "compilecache.persist.midwrite", "mode": "kill"}]))
CompileCache({cache!r}).load_or_compile(CacheJob(
    entry_id="chaos", jitted=jax.jit(lambda x: x + 1.0),
    abstract_args=(jax.ShapeDtypeStruct((4,), jnp.float32),)))
raise SystemExit("kill fault did not fire")
"""

_CACHE_CORRUPT = """
import numpy as np, jax, jax.numpy as jnp
from mlops_tpu import faults
from mlops_tpu.compilecache.cache import (
    CacheJob, CompileCache, serialization_available)
if not serialization_available():
    print("NO-SERIALIZATION"); raise SystemExit(0)
job = CacheJob(entry_id="chaos", jitted=jax.jit(lambda x: x * 3.0),
               abstract_args=(jax.ShapeDtypeStruct((4,), jnp.float32),))
CompileCache({cache!r}).load_or_compile(job)  # persist a good artifact
faults.arm(faults.FaultPlan.from_rules(
    [{"point": "compilecache.read", "mode": "corrupt", "flip_bits": 8}]))
cache = CompileCache({cache!r})
fn = cache.load_or_compile(job)  # corrupt read -> discard -> recompile
faults.disarm()
stats = cache.stats()
assert stats["discards"] == 1 and stats["misses"] == 1, stats
np.testing.assert_allclose(
    np.asarray(fn(jnp.arange(4, dtype=jnp.float32))),
    np.arange(4, dtype=np.float32) * 3.0)
healed = CompileCache({cache!r})
healed.load_or_compile(job)
assert healed.stats()["hits"] == 1, healed.stats()  # store self-healed
print("CORRUPTION-HANDLED")
"""


# --------------------------------------------------- mid-regrid kill -9
# Phase 1: a hot regrid (ISSUE 18 gridtuner) is SIGKILLed between its
# warm phase and its swap — the most in-flight state a regrid ever
# holds. A serving hammer runs throughout, so the kill lands on a plane
# that is actively dispatching.
_REGRID_KILL = """
import threading, time
from mlops_tpu import faults
from mlops_tpu.autotune import apply_plan
from mlops_tpu.bundle import load_bundle
from mlops_tpu.compilecache.cache import CompileCache
from mlops_tpu.serve.engine import InferenceEngine

engine = InferenceEngine(
    load_bundle({bundle!r}), buckets=(1, 8),
    compile_cache=CompileCache({cache!r}), enable_grouping=False)
engine.warmup()
record = [{record!r}]
ref = engine.predict_records(record)["predictions"]
stop = threading.Event()
def hammer():
    while not stop.is_set():
        assert engine.predict_records(record)["predictions"] == ref
        time.sleep(0.005)
t = threading.Thread(target=hammer, daemon=True); t.start()
time.sleep(0.1)
faults.arm(faults.FaultPlan.from_rules(
    [{"point": "autotune.regrid.midswap", "mode": "kill"}]))
apply_plan(engine, (1, 2, 8))
raise SystemExit("kill fault did not fire")
"""

# Phase 2: a fresh process over the SAME bundle + compile cache must
# serve bit-identically (the crash left nothing durable mid-mutation),
# complete the interrupted regrid cleanly, keep responses bit-stable
# across the swap, and roll back in one call.
_REGRID_RECOVER = """
from mlops_tpu.autotune import apply_plan
from mlops_tpu.bundle import load_bundle
from mlops_tpu.compilecache.cache import CompileCache
from mlops_tpu.serve.engine import InferenceEngine

engine = InferenceEngine(
    load_bundle({bundle!r}), buckets=(1, 8),
    compile_cache=CompileCache({cache!r}), enable_grouping=False)
engine.warmup()
record = [{record!r}]
before = engine.predict_records(record)
gen0 = engine.grid_generation
gen = apply_plan(engine, (1, 2, 8))  # the crashed regrid, re-run clean
assert gen == gen0 + 1 and tuple(engine.buckets) == (1, 2, 8)
assert engine.predict_records(record) == before, "regrid changed bytes"
engine.rollback()
assert tuple(engine.buckets) == (1, 8)
assert engine.predict_records(record) == before, "rollback changed bytes"
print("REGRID-RECOVERED")
"""


def regrid_kill_scenario(tmp: str, bundle: str) -> None:
    cache_dir = os.path.join(tmp, "regrid-cache")
    script = (
        _REGRID_KILL
        .replace("{bundle!r}", repr(bundle))
        .replace("{cache!r}", repr(cache_dir))
        .replace("{record!r}", repr(RECORD))
    )
    run_subprocess_scenario("mid-regrid kill -9", script, expect_kill=True)
    recover = run_subprocess_scenario(
        "post-crash regrid recovery",
        _REGRID_RECOVER
        .replace("{bundle!r}", repr(bundle))
        .replace("{cache!r}", repr(cache_dir))
        .replace("{record!r}", repr(RECORD)),
    )
    assert "REGRID-RECOVERED" in recover.stdout


def midwrite_and_corruption_scenarios(tmp: str) -> None:
    state = os.path.join(tmp, "reservoir-state")
    run_subprocess_scenario(
        "reservoir mid-write kill",
        _RESERVOIR_KILL.replace("{state!r}", repr(state)),
        expect_kill=True,
    )
    assert not os.path.exists(os.path.join(state, "reservoir.npz")), (
        "torn reservoir snapshot landed at the target path"
    )

    target = os.path.join(tmp, "ckpt.bin")
    run_subprocess_scenario(
        "atomic_write mid-write kill",
        _ATOMIC_KILL.replace("{target!r}", repr(target)),
        expect_kill=True,
    )
    with open(target, "rb") as f:
        assert f.read() == b"GOOD" * 1024, "torn atomic_write payload"

    cache_dir = os.path.join(tmp, "chaos-cache")
    proc = subprocess.run(
        [sys.executable, "-c",
         _CACHE_KILL.replace("{cache!r}", repr(cache_dir))],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=600, cwd=REPO,
    )
    if "NO-SERIALIZATION" in proc.stdout:
        print("# chaos-smoke: cache scenarios skipped (no serialization)",
              flush=True)
        return
    assert proc.returncode == -signal.SIGKILL, (
        proc.returncode, proc.stderr[-1000:]
    )
    leftovers = [
        os.path.join(dirpath, f)
        for dirpath, _, files in os.walk(cache_dir)
        for f in files if f.endswith(".jaxexe")
    ]
    assert leftovers == [], f"torn cache artifact landed: {leftovers}"
    print("# chaos-smoke: scenario OK — cache persist mid-write kill",
          flush=True)

    corrupt = run_subprocess_scenario(
        "cache corruption on read",
        _CACHE_CORRUPT.replace("{cache!r}", repr(cache_dir)),
    )
    assert "CORRUPTION-HANDLED" in corrupt.stdout


# ------------------------------------------------------ live-plane chaos
def live_plane_scenarios(tmp: str, bundle: str) -> None:
    plan_path = os.path.join(tmp, "chaos.toml")
    with open(plan_path, "w") as f:
        f.write(CHAOS_PLAN)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["MLOPS_TPU_FAULTS"] = plan_path

    port = free_port()
    server = subprocess.Popen(
        [
            sys.executable, "-m", "mlops_tpu", "serve", "--workers", "2",
            "serve.host=127.0.0.1", f"serve.port={port}",
            f"serve.model_directory={bundle}",
            "serve.warmup_batch_sizes=1,8", "serve.max_batch=8",
            # Tiny admission so the overload burst actually sheds, and the
            # chaos-tuned drain knobs (the ex-hard-coded 30/35/50) so the
            # drain assertion exercises their wiring.
            "serve.ring_slots_small=4", "serve.ring_slots_large=1",
            "serve.request_timeout_s=6",
            # Tier routing + brownout (ISSUE 19), DRILL-TUNED: a demote
            # depth of 0.2 on the 5-slot per-worker partition means ONE
            # busy slot activates the governor, so the brownout scenario
            # below can prove demotions precede the first shed without
            # needing a seeded stall. (The tiny bundle has no gated
            # quant tier: the ladder collapses to the default program —
            # demotion counters must rise anyway, bits must not change.)
            "serve.tier_routing=true",
            "serve.brownout_demote_depth=0.2",
            "serve.brownout_restore_depth=0.1",
            "serve.drain_deadline_s=8", "serve.zygote_join_deadline_s=10",
            "serve.engine_zygote_join_s=16",
            # AOT cache: the first boot compiles + persists; the engine
            # RESPAWN in the kill scenario warm-starts by deserializing,
            # which is what keeps the brownout window tight.
            f"cache.dir={os.path.join(tmp, 'chaos-serve-cache')}",
            # sloscope (ISSUE 14), DRILL-TUNED: seconds-scale burn
            # windows, a 0.5 s tick, and a burn threshold of 1.0 so the
            # stall scenario's seeded 504s provably cross it — the
            # acceptance is alert_active flipping within two ticks and
            # a flight-recorder dump whose timeline carries the
            # offending spans (tracewire armed for exactly that).
            "slo.enabled=true", "slo.tick_s=0.5",
            "slo.fast_burn_threshold=1.0", "slo.slow_burn_threshold=1.0",
            "slo.fast_short_s=10", "slo.fast_long_s=30",
            "slo.slow_short_s=45", "slo.slow_long_s=90",
            "slo.flightrec_cooldown_s=2",
            f"slo.flightrec_dir={os.path.join(tmp, 'flightrec')}",
            "trace.enabled=true",
            f"trace.dir={os.path.join(tmp, 'chaos-traces')}",
        ],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    log_lines: list[str] = []
    pump = threading.Thread(
        target=lambda: log_lines.extend(iter(server.stdout.readline, "")),
        daemon=True,
    )
    pump.start()
    statuses: list[int] = []
    statuses_lock = threading.Lock()
    body = json.dumps([RECORD]).encode()

    def record_status(status: int) -> None:
        with statuses_lock:
            statuses.append(status)

    try:
        print("# chaos-smoke: waiting for readiness (faults armed)",
              flush=True)
        deadline = time.time() + 600
        ready = False
        while time.time() < deadline and not ready:
            if server.poll() is not None:
                print("\n".join(log_lines[-60:]))
                raise SystemExit("server died before readiness")
            try:
                status, _ = get(f"http://127.0.0.1:{port}/healthz/ready", 5)
                ready = status == 200
            except (urllib.error.URLError, OSError, urllib.error.HTTPError):
                pass
            if not ready:
                time.sleep(1.0)
        assert ready, "server never became ready under the armed plan"
        assert any("fault injection ARMED" in line for line in log_lines), (
            "the env plan did not arm in the serve processes"
        )

        # ---- scenario: engine stall -> deadline 504s, no hangs --------
        def budgeted_client(n: int) -> None:
            for _ in range(n):
                status, _, _ = raw_predict(
                    port, body, headers={"x-request-deadline-ms": "400"},
                )
                record_status(status)

        threads = [
            threading.Thread(target=budgeted_client, args=(20,))
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not any(t.is_alive() for t in threads), "stalled client hung"
        with statuses_lock:
            got_504 = statuses.count(504)
        assert got_504 >= 1, (
            f"seeded stalls produced no 504 in {len(statuses)} requests"
        )
        print(f"# chaos-smoke: engine stall OK ({got_504} deadline 504s "
              f"in {len(statuses)} budgeted requests)", flush=True)

        # ---- scenario: the 504 storm burns the error budget ----------
        # (ISSUE 14 acceptance) The stall's 504s must flip
        # mlops_tpu_alert_active within two evaluation ticks
        # (tick_s=0.5 -> allow 2 ticks + one watchdog pass of margin for
        # the scrape itself), and a front end watching the shm alert
        # flags must drop a flight-recorder dump whose timeline carries
        # the offending 504 evidence (spans included — tracewire armed).
        alert_deadline = time.time() + 10.0
        burn_alert_on = False
        while time.time() < alert_deadline and not burn_alert_on:
            status, text = get(f"http://127.0.0.1:{port}/metrics", 15)
            assert status == 200
            burn_alert_on = any(
                line.startswith(
                    'mlops_tpu_alert_active{alert="availability_fast_burn"'
                ) and line.endswith(" 1")
                for line in text.decode().splitlines()
            )
            if not burn_alert_on:
                time.sleep(0.5)
        assert burn_alert_on, (
            "availability_fast_burn never flipped after the 504 storm"
        )
        status, text = get(f"http://127.0.0.1:{port}/healthz", 15)
        verdict = json.loads(text)
        assert status == 200 and verdict["verdict"] == "degraded", verdict
        dump_deadline = time.time() + 15.0
        flightrec_dir = os.path.join(tmp, "flightrec")
        offending = None
        while time.time() < dump_deadline and offending is None:
            names = (
                sorted(os.listdir(flightrec_dir))
                if os.path.isdir(flightrec_dir) else []
            )
            for name in names:
                path = os.path.join(flightrec_dir, name)
                try:
                    dump = json.loads(open(path).read())
                except (OSError, ValueError):
                    continue  # a dump mid-rename; the next pass reads it
                has_504 = any(
                    e.get("status") == 504
                    for e in dump.get("events", [])
                    if e.get("kind") in ("request", "span")
                )
                has_span = any(
                    e.get("kind") == "span" and e.get("status") == 504
                    for e in dump.get("events", [])
                )
                if has_504 and has_span:
                    offending = path
                    break
            if offending is None:
                time.sleep(0.5)
        assert offending is not None, (
            "no flight-recorder dump carrying the offending 504 spans"
        )
        # The CLI renders it (timeline includes the 504 evidence).
        render = subprocess.run(
            [sys.executable, "-m", "mlops_tpu", "flightrec", offending],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
        )
        assert render.returncode == 0, render.stderr[-1000:]
        assert "504" in render.stderr
        print(f"# chaos-smoke: burn alert + flight dump OK ({offending})",
              flush=True)

        # ---- wire-contract probes ------------------------------------
        status, _, _ = raw_predict(port, json.dumps([RECORD] * 9).encode())
        record_status(status)
        assert status == 413, status
        status, _, _ = raw_predict(port, json.dumps([{"age": "x"}]).encode())
        record_status(status)
        assert status == 422, status

        # ---- scenario: slow client does not wedge the plane ----------
        slow_done: dict = {}

        def slow_client() -> None:
            payload = (
                f"POST /predict HTTP/1.1\r\nhost: slow\r\n"
                f"content-type: application/json\r\n"
                f"content-length: {len(body)}\r\n"
                f"connection: close\r\n\r\n"
            ).encode() + body
            with socket.create_connection(
                ("127.0.0.1", port), timeout=30
            ) as s:
                s.settimeout(30)
                for i in range(0, len(payload), 40):
                    s.sendall(payload[i : i + 40])
                    time.sleep(0.05)
                data = b""
                while True:
                    chunk = s.recv(65536)
                    if not chunk:
                        break
                    data += chunk
            slow_done["status"] = int(data.split(b" ")[1])

        dribbler = threading.Thread(target=slow_client)
        dribbler.start()
        fast_during_slow = []
        for _ in range(6):
            status, _, _ = raw_predict(port, body)
            record_status(status)
            fast_during_slow.append(status)
        dribbler.join(timeout=60)
        assert not dribbler.is_alive(), "slow client hung the smoke"
        record_status(slow_done["status"])
        assert slow_done["status"] in ALLOWED_STATUSES
        assert any(s == 200 for s in fast_during_slow), (
            "no fast request served while the slow client dribbled"
        )
        print("# chaos-smoke: slow client OK (plane served "
              f"{fast_during_slow.count(200)}/6 during the dribble)",
              flush=True)

        # ---- metrics scrape #1 (monotonicity baseline) ---------------
        status, text = get(f"http://127.0.0.1:{port}/metrics", 30)
        assert status == 200
        first = parse_counters(text.decode())
        assert any("mlops_tpu_deadline_expired_total" in k for k in first)
        assert any("mlops_tpu_degraded_dispatch_total" in k for k in first)

        # ---- scenario: brownout demotes BEFORE the overload shed -----
        # (ISSUE 19) Phase 1 offers sustained concurrency UNDER the
        # per-worker partition (4 loops vs 5 slots — a shed is
        # impossible by construction): the armed governor's demotion
        # counters must rise while every shed counter stays flat.
        # Phase 2 is the 10x-partition overload burst: 503s become
        # legal, statuses stay inside the contract set, and the
        # demotion counters from phase 1 prove the plane spent fidelity
        # before it ever spent availability.
        def counter_sum(counters: dict, prefix: str) -> float:
            return sum(
                v for k, v in counters.items() if k.startswith(prefix)
            )

        def shed_sum(counters: dict) -> float:
            return counter_sum(
                counters, "mlops_tpu_shed_total"
            ) + counter_sum(counters, "mlops_tpu_tenant_quota_shed_total")

        status, text = get(f"http://127.0.0.1:{port}/metrics", 30)
        assert status == 200
        base = parse_counters(text.decode())
        base_demote = counter_sum(base, "mlops_tpu_tier_demotions_total")
        base_shed = shed_sum(base)

        def brownout_client() -> None:
            for _ in range(30):
                status, _, _ = raw_predict(port, body, timeout=30)
                record_status(status)

        browners = [
            threading.Thread(target=brownout_client) for _ in range(4)
        ]
        for t in browners:
            t.start()
        for t in browners:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in browners), (
            "brownout client hung"
        )
        status, text = get(f"http://127.0.0.1:{port}/metrics", 30)
        assert status == 200
        mid = parse_counters(text.decode())
        mid_demote = counter_sum(mid, "mlops_tpu_tier_demotions_total")
        assert mid_demote > base_demote, (
            "governor never demoted under sub-partition pressure "
            f"(demote counter {base_demote} -> {mid_demote})"
        )
        assert shed_sum(mid) == base_shed, (
            "a shed fired while offered load was under the partition — "
            "brownout must come first"
        )
        print(
            "# chaos-smoke: brownout phase OK "
            f"(+{mid_demote - base_demote:.0f} demotions, zero sheds)",
            flush=True,
        )

        def burst_client() -> None:
            try:
                status, _, _ = raw_predict(port, body, timeout=30)
                record_status(status)
            except OSError:
                pass  # connection refused under burst = backpressure, fine

        burst = [threading.Thread(target=burst_client) for _ in range(50)]
        for t in burst:
            t.start()
        for t in burst:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in burst), "burst client hung"
        print("# chaos-smoke: overload burst OK", flush=True)

        # ---- scenario: worker kill -> supervisor respawn -------------
        spawn_line = next(line for line in log_lines if "spawned" in line)
        pids = [
            int(p) for p in
            re.findall(r"\d+", spawn_line.split("(pids", 1)[1])
        ]
        os.kill(pids[0], signal.SIGKILL)
        deadline = time.time() + 30
        while time.time() < deadline and not any(
            "respawning" in line for line in log_lines
        ):
            time.sleep(0.2)
        assert any("respawning" in line for line in log_lines), (
            "supervisor never respawned the SIGKILLed front end"
        )
        deadline = time.time() + 30
        served = False
        while time.time() < deadline and not served:
            try:
                status, _, _ = raw_predict(port, body)
                record_status(status)
                served = status == 200
            except OSError:
                time.sleep(0.2)
        assert served, "plane stopped serving after the worker kill"
        print("# chaos-smoke: worker kill OK (respawned, still serving)",
              flush=True)

        # ---- scenario: ENGINE kill -> respawn + replay (ISSUE 11) ----
        # Budgeted hammer traffic across a SIGKILL of the engine process:
        # requests in flight at kill time park and are replayed by the
        # respawned incarnation; 504 is legal ONLY on true budget expiry
        # (budget = the 5 s header here, tighter than request_timeout_s);
        # every 200 body must be identical to the pre-kill body (replay
        # bit-identity: same AOT artifacts, same slab input, pure packed
        # predict).
        engine_line = next(line for line in log_lines if "engine pid" in line)
        engine_pid = int(re.search(r"engine pid (\d+)", engine_line).group(1))
        status, _, ref_body = raw_predict(port, body)
        assert status == 200, "no reference response before the engine kill"
        kill_results: list[tuple[int, float, bytes]] = []
        kill_lock = threading.Lock()
        hammer_stop = threading.Event()

        def kill_hammer() -> None:
            while not hammer_stop.is_set():
                t0 = time.perf_counter()
                try:
                    s_, _, b_ = raw_predict(
                        port, body,
                        headers={"x-request-deadline-ms": "5000"},
                        timeout=30,
                    )
                except OSError:
                    continue  # accept-queue churn during the brownout
                with kill_lock:
                    kill_results.append(
                        (s_, time.perf_counter() - t0, b_)
                    )

        hammers = [threading.Thread(target=kill_hammer) for _ in range(3)]
        for t in hammers:
            t.start()
        time.sleep(1.0)  # traffic flowing; some requests in flight
        os.kill(engine_pid, signal.SIGKILL)
        deadline = time.time() + 60
        while time.time() < deadline and not any(
            "engine replica" in line and "respawning" in line
            for line in log_lines
        ):
            time.sleep(0.2)
        assert any(
            "engine replica" in line and "respawning" in line
            for line in log_lines
        ), "supervisor never respawned the SIGKILLed engine"
        # Keep hammering until the respawned engine serves again.
        deadline = time.time() + 180
        recovered = False
        while time.time() < deadline and not recovered:
            with kill_lock:
                n_before = len(kill_results)
            time.sleep(0.5)
            with kill_lock:
                recovered = any(
                    s_ == 200 for s_, _, _ in kill_results[n_before:]
                )
        hammer_stop.set()
        for t in hammers:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in hammers), "kill hammer hung"
        assert recovered, "plane never recovered after the engine kill"
        with kill_lock:
            kill_statuses = [s_ for s_, _, _ in kill_results]
            for s_, elapsed, _ in kill_results:
                record_status(s_)
                assert s_ in {200, 503, 504}, (
                    f"status {s_} during the engine-kill window"
                )
                if s_ == 504:
                    assert elapsed <= 6.5, (
                        f"504 took {elapsed:.2f}s — outside its 5 s budget"
                    )
            for s_, _, b_ in kill_results:
                if s_ == 200:
                    assert b_ == ref_body, (
                        "a 200 body across the respawn differs from the "
                        "pre-kill reference (replay bit-identity broken)"
                    )
        tally_kill = {
            s_: kill_statuses.count(s_) for s_ in sorted(set(kill_statuses))
        }
        print(
            "# chaos-smoke: engine kill OK (respawned + replayed; "
            f"window tally {tally_kill})", flush=True,
        )

        # ---- metrics scrape #2: counters are monotone ----------------
        status, text = get(f"http://127.0.0.1:{port}/metrics", 30)
        assert status == 200
        second = parse_counters(text.decode())
        regressions = {
            k: (first[k], second[k])
            for k in first
            if k in second and second[k] < first[k]
        }
        assert not regressions, f"non-monotone counters: {regressions}"
        assert second.get("mlops_tpu_engine_respawn_total", 0) >= 1, (
            "engine_respawn_total missing or zero after the engine kill"
        )

        # ---- the global status contract ------------------------------
        with statuses_lock:
            illegal = sorted({s for s in statuses if s not in ALLOWED_STATUSES})
            tally = {s: statuses.count(s) for s in sorted(set(statuses))}
        assert not illegal, f"statuses outside the contract set: {illegal}"
        print(f"# chaos-smoke: status tally {tally}", flush=True)

        # ---- clean drain under the chaos-tuned knobs -----------------
        server.send_signal(signal.SIGTERM)
        rc = server.wait(timeout=60)
        pump.join(timeout=10)
        log = "\n".join(log_lines)
        assert rc == 0, f"server exited {rc}\n{log[-2000:]}"
        assert "drained" in log, log[-2000:]
        assert "Task was destroyed" not in log, log[-2000:]
        print("# chaos-smoke: drain OK (exit 0 under chaos drain knobs)",
              flush=True)
    finally:
        if server.poll() is None:
            server.kill()
            server.wait(timeout=10)


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="chaos-smoke-")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")

    print("# chaos-smoke: mid-write kill + corruption scenarios", flush=True)
    midwrite_and_corruption_scenarios(tmp)

    print("# chaos-smoke: training tiny bundle", flush=True)
    train = subprocess.run(
        [
            sys.executable, "-m", "mlops_tpu", "train",
            "data.rows=3000",
            "model.hidden_dims=32,32", "model.embed_dim=4",
            "train.steps=100", "train.eval_every=100",
            "train.batch_size=256",
            f"registry.root={tmp}/registry", f"registry.run_root={tmp}/runs",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    if train.returncode != 0:
        print(train.stdout[-2000:], train.stderr[-2000:], sep="\n")
        raise SystemExit("train failed")
    bundle = json.loads(train.stdout.strip().splitlines()[-1])["bundle"]
    print(f"# chaos-smoke: bundle at {bundle}", flush=True)

    print("# chaos-smoke: mid-regrid kill scenario", flush=True)
    regrid_kill_scenario(tmp, bundle)

    live_plane_scenarios(tmp, bundle)
    print("# chaos-smoke: OK (all seeded scenarios green)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
