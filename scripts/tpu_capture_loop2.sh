#!/bin/bash
# Round-5 TPU capture loop (v2): probe the axon tunnel every ~3 min; on a
# healthy probe run the full flagship bench; if that lands on TPU, also
# attempt an FT-Transformer bench (VERDICT r4 #8's exact-bulk row — the
# FT run records score.exact bulk via bulk_rows_per_s_pipelined).
# Stops on first full TPU capture or after ~11h of attempts.
LOG=/root/repo/runs/bench/capture_r5.log
echo "$(date -Is) capture loop v2 start (pid $$)" >> "$LOG"
for i in $(seq 1 220); do
  if timeout -k 10 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    ts=$(date +%m%d_%H%M%S)
    echo "$(date -Is) probe $i OK -> bench attempt $ts" >> "$LOG"
    out=/root/repo/runs/bench/tpu_r5_${ts}.json
    BENCH_TPU_RETRIES=2 timeout -k 30 2400 python /root/repo/bench.py \
      > "$out" 2> "${out%.json}.log"
    rc=$?
    if grep -q '"device": "TPU' "$out" 2>/dev/null; then
      echo "$(date -Is) TPU FLAGSHIP BENCH CAPTURED rc=$rc -> $out" >> "$LOG"
      ftout=/root/repo/runs/bench/tpu_r5_${ts}_ft.json
      BENCH_MODEL=ft_transformer BENCH_TPU_RETRIES=2 timeout -k 30 2400 \
        python /root/repo/bench.py > "$ftout" 2> "${ftout%.json}.log"
      if grep -q '"device": "TPU' "$ftout" 2>/dev/null; then
        echo "$(date -Is) TPU FT BENCH CAPTURED -> $ftout" >> "$LOG"
      else
        echo "$(date -Is) FT bench missed TPU (kept $ftout)" >> "$LOG"
      fi
      exit 0
    fi
    echo "$(date -Is) bench rc=$rc but device not TPU (kept $out)" >> "$LOG"
  else
    echo "$(date -Is) probe $i dead" >> "$LOG"
  fi
  sleep 180
done
echo "$(date -Is) capture loop v2 exhausted" >> "$LOG"
exit 1
