"""CI gate: the latest committed BENCH round still honors its contract.

The bench key-contract tests (tests/test_*.py "bench key contract"
sections) pin that the STAGE FUNCTIONS emit their keys; this script pins
that the latest COMMITTED round actually carries them — a bench run that
silently lost a stage (a guarded stage swallowing its error into
``*_error``) must fail CI here, not be discovered during the next
regression hunt. On top of key presence, the derived headline ratios
must sit inside their declared bounds: numbers that drift outside them
mean either a real regression or a broken measurement, and both gate.

Rounds are the driver wrapper files ``BENCH_r*.json`` at the repo root
(``parsed`` holds the bench JSON; a bare bench line is accepted too).

On top of the round contract, the committed alert rules
(``configs/alerts/*.yml``) are validated against the series registry
rebuilt statically from the renderers (``analysis/seriesreg.py`` — the
same registry TPU502 consumes), so an alert referencing a renamed or
deleted series fails the bench gate even if nobody re-ran the linter.

Run from the repo root: ``python scripts/bench_check.py``
(exit 0 = contract holds, 1 = named violations, 2 = no rounds found).
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# Every headline key a committed round must carry. Satellite/diagnostic
# keys (breakdowns, per-axis splits) ride along but are not gated here —
# the stage-level key-contract tests own those.
HEADLINE_KEYS = (
    # batch-1 hot path
    "value", "p99_ms", "batch1_req_per_s", "lock_wait_ms",
    # device monitor + faultline + tracewire + sloscope overhead keys
    "monitor_fetch_per_s", "fault_overhead_pct", "degraded_p99_ms",
    "trace_overhead_pct", "padding_waste_pct", "useful_rows_per_s",
    "slo_overhead_pct", "slo_armed_p50_ms",
    # bulk + streaming + quant tier (ISSUE 17)
    "bulk_rows_per_s_bulkpath", "bulk_stream_rows_per_s_pipelined",
    "quant_rows_per_s", "quant_auc_delta",
    # continuous micro-batching (ISSUE 17)
    "batch1_p50_ms_continuous",
    # roofline + cold start
    "mfu_bulk", "engine_cold_start_s", "engine_warm_start_s",
    # serve planes
    "engine_group_req_per_s", "http_req_per_s_best",
    "http_vs_engine_ratio", "shed_503_pct",
    # traffic-shape autotuner (ISSUE 18)
    "autotune_goodput_gain_pct", "regrid_downtime_ms",
    # tiered SLO serving (ISSUE 19)
    "tier_routed_req_per_s", "brownout_goodput_gain_pct",
    # tenancy + replica set + survivability + lifecycle
    "tenants_shared_exec_count", "starvation_cold_p99_ratio",
    "replica_scaling_efficiency", "engine_respawn_gap_ms",
    "swap_downtime_ms",
    # training
    "train_rows_per_s", "model_auc",
)

# (key, lower, upper): the declared bounds for the derived ratios. Wide
# on purpose — they catch broken measurements and real cliffs, not
# box-to-box noise.
BOUNDS = (
    # E-replica fan-out must keep scaling usefully (BENCH_r07: 0.845).
    ("replica_scaling_efficiency", 0.5, 1.05),
    # HTTP goodput vs raw engine capacity (BENCH_r05+: ~0.68; the 0.85
    # target is ROADMAP residue — the lower bound is the regression
    # floor, not the target). The floor sits at 0.2 because the
    # DENOMINATOR is noisy on the 1-core box: engine_group_req_per_s
    # swung 3.5k-4.5k across BENCH_r09-r11 while HTTP held ~1.0-1.2k,
    # so a tighter floor gates engine speedups instead of HTTP cliffs.
    ("http_vs_engine_ratio", 0.2, 1.1),
    # sloscope armed overhead on batch-1 p50: ~0 disarmed by design;
    # the armed delta must stay single-digit percent (negative values
    # are measurement noise on a quiet box).
    ("slo_overhead_pct", -10.0, 10.0),
    # Quant tier (ISSUE 17): the int8/bf16 student must beat the f32 bulk
    # path by the acceptance ratio, at a held-out AUC delta no worse than
    # the promotion gate's epsilon (LifecycleConfig.max_auc_drop).
    ("quant_speedup_vs_student", 2.0, 1000.0),
    ("quant_auc_delta", -0.01, 1.0),
    # Gridtuner (ISSUE 18): the autotuned grid must beat the hand grid
    # on the skewed trace (measured, not predicted — the floor is the
    # acceptance claim), and the hot swap must stay pointer-cheap: the
    # warm happens off-path, so worst-observed added latency during the
    # swap window stays far under one dispatch's worth of stall.
    ("autotune_goodput_gain_pct", 0.0, 100000.0),
    ("regrid_downtime_ms", 0.0, 250.0),
    # Tierroute (ISSUE 19): the cheap class routed through its gated
    # tier must still clear a real per-request rate (broken routing
    # reads ~0), and at 10x load brownout must beat pure shed on useful
    # responses/s — the acceptance claim, so 0 is the regression floor.
    ("tier_routed_req_per_s", 50.0, 1e9),
    ("brownout_goodput_gain_pct", 0.0, 100000.0),
)


def latest_round() -> tuple[Path, dict] | None:
    rounds = sorted(
        REPO.glob("BENCH_r*.json"),
        key=lambda p: int(re.search(r"(\d+)", p.stem).group(1)),
    )
    if not rounds:
        return None
    path = rounds[-1]
    doc = json.loads(path.read_text())
    # Driver wrapper ({"parsed": {...}}) or a bare bench line.
    return path, doc.get("parsed", doc)


def alert_rule_problems() -> list[str]:
    """Every ``mlops_tpu_*`` token in the committed alert rules must name
    a series some renderer actually emits. Group/alert identifier lines
    (``name:``/``alert:``) are labels, not references."""
    sys.path.insert(0, str(REPO))  # scripts/ is sys.path[0] when run
    from mlops_tpu.analysis.contracts import _YML_IDENTIFIER_LINE
    from mlops_tpu.analysis.seriesreg import registry_from_paths

    registry = registry_from_paths([REPO / "mlops_tpu"])
    if registry is None:
        return ["series registry: no TPULINT_SERIES_PLANES manifest "
                "found under mlops_tpu/"]
    known = registry.names()
    token_re = re.compile(r"mlops_tpu_\w+")
    problems: list[str] = []
    for rules in sorted((REPO / "configs" / "alerts").glob("*.yml")):
        for lineno, line in enumerate(
            rules.read_text().splitlines(), start=1
        ):
            if _YML_IDENTIFIER_LINE.match(line):
                continue
            for token in token_re.findall(line):
                if token not in known:
                    problems.append(
                        f"{rules.name}:{lineno}: alert references "
                        f"series {token!r}, which no renderer emits"
                    )
    return problems


def main() -> int:
    found = latest_round()
    if found is None:
        print("bench-check: no BENCH_r*.json rounds committed",
              file=sys.stderr)
        return 2
    path, payload = found
    problems: list[str] = []
    if payload.get("error"):
        problems.append(f"round is an error line: {payload['error']}")
    for key in HEADLINE_KEYS:
        if key not in payload:
            problems.append(f"missing headline key: {key}")
    for key, lower, upper in BOUNDS:
        value = payload.get(key)
        if not isinstance(value, (int, float)):
            continue  # the missing-key check above already names it
        if not lower <= float(value) <= upper:
            problems.append(
                f"{key}={value} outside declared bounds "
                f"[{lower}, {upper}]"
            )
    problems.extend(alert_rule_problems())
    if problems:
        print(f"bench-check: {path.name} violates the round contract:",
              file=sys.stderr)
        for problem in problems:
            print(f"  - {problem}", file=sys.stderr)
        return 1
    print(
        f"bench-check: {path.name} OK — {len(HEADLINE_KEYS)} headline "
        f"keys present, {len(BOUNDS)} bounds hold, alert rules match "
        "the series registry"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
