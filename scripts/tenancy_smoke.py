"""CI live-server tenancy smoke: TWO tenants on one plane, drift ONE,
assert per-tenant lifecycle ISOLATION with zero non-200s on the
undrifted tenant.

The end-to-end proof that multi-tenant multiplexing works as DEPLOYED
(real CLI with ``--tenants``, real process, real HTTP with the
``x-tenant`` header), not just under the in-process test harness:

1. train a tiny bundle through the real CLI; tenant ``beta`` serves a
   COPY of it (identical architecture — the fleet must log the
   shared-compiled-entries adoption),
2. write a tenants.toml (alpha default + beta) and launch
   ``mlops-tpu serve --tenants`` single-process with
   ``lifecycle.enabled=true`` and tight loop knobs — one lifecycle
   controller PER TENANT on tenant-namespaced state dirs,
3. hammer /predict for BOTH tenants from background threads, counting
   every non-200 per tenant,
4. phase 2: ALPHA's traffic turns DRIFTED (numerics x10) while beta's
   stays normal; poll /metrics until
   ``mlops_tpu_drift_trigger_total{tenant="alpha"}`` fires and
   ``mlops_tpu_bundle_generation{tenant="alpha"}`` reaches 2 with a
   promoted outcome,
5. assert ISOLATION: beta's generation is still 1, beta's trigger count
   is still 0, and beta saw ZERO non-200s across alpha's whole
   trigger/retrain/shadow/swap window (alpha too — the swap is
   zero-downtime per tenant),
6. SIGTERM and assert a clean drain (exit 0, no leaked tasks).

Run from the repo root: `python scripts/tenancy_smoke.py` (CI pins
JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def get(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def metric_value(text: str, name: str, labels: str = "") -> float | None:
    pattern = (
        re.escape(name + ("{" + labels + "}" if labels else ""))
        + r" ([-0-9.e+]+)"
    )
    match = re.search(pattern, text)
    return float(match.group(1)) if match else None


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="tenancy-smoke-")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")

    sys.path.insert(0, REPO)
    from mlops_tpu.data import generate_synthetic, write_csv_columns
    from mlops_tpu.schema import SCHEMA

    columns, labels = generate_synthetic(1500, seed=3)
    drifted = {k: list(v) for k, v in columns.items()}
    for feat in SCHEMA.numeric:
        drifted[feat.name] = [v * 10.0 for v in drifted[feat.name]]
    labeled_csv = f"{tmp}/labeled.csv"
    write_csv_columns(labeled_csv, drifted, labels)

    def records(cols, n, offset=0):
        names = [f.name for f in SCHEMA.categorical] + [
            f.name for f in SCHEMA.numeric
        ]
        return [
            {name: cols[name][offset + i] for name in names}
            for i in range(n)
        ]

    normal_body = json.dumps(records(columns, 8)).encode()
    drifted_body = json.dumps(records(drifted, 8, offset=16)).encode()

    print("# tenancy-smoke: training tiny bundle", flush=True)
    train = subprocess.run(
        [
            sys.executable, "-m", "mlops_tpu", "train",
            "data.rows=3000",
            "model.hidden_dims=32,32", "model.embed_dim=4",
            "train.steps=100", "train.eval_every=100",
            "train.batch_size=256",
            f"registry.root={tmp}/registry", f"registry.run_root={tmp}/runs",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    if train.returncode != 0:
        print(train.stdout[-2000:], train.stderr[-2000:], sep="\n")
        raise SystemExit("train failed")
    alpha_bundle = json.loads(train.stdout.strip().splitlines()[-1])["bundle"]
    # Tenant beta: an architecture-identical copy — its own bundle ref,
    # its own lifecycle, the incumbent's compiled entries (adopted).
    beta_bundle = f"{tmp}/beta-bundle"
    shutil.copytree(alpha_bundle, beta_bundle)

    tenants_toml = f"{tmp}/tenants.toml"
    with open(tenants_toml, "w") as f:
        f.write(
            'default_tenant = "alpha"\n'
            "[[tenant]]\n"
            'name = "alpha"\n'
            f'bundle_dir = "{alpha_bundle}"\n'
            "weight = 1.0\n"
            "[[tenant]]\n"
            'name = "beta"\n'
            f'bundle_dir = "{beta_bundle}"\n'
            "weight = 1.0\n"
        )

    port = free_port()
    server = subprocess.Popen(
        [
            sys.executable, "-m", "mlops_tpu", "serve",
            "--tenants", tenants_toml,
            "serve.host=127.0.0.1", f"serve.port={port}",
            "serve.warmup_batch_sizes=1,8", "serve.max_batch=8",
            "serve.batch_window_ms=0",  # solo path: deterministic latency
            "serve.monitor_fetch_every_s=0.5",
            "lifecycle.enabled=true",
            f"lifecycle.dir={tmp}/lifecycle",
            f"lifecycle.labeled_path={labeled_csv}",
            "lifecycle.retrain_steps=50",
            "lifecycle.min_labeled_rows=500",
            "lifecycle.min_window_rows=32",
            "lifecycle.hysteresis_windows=2",
            "lifecycle.cooldown_s=2",
            "lifecycle.tick_s=0.25",
            "lifecycle.mirror_fraction=1.0",
            "lifecycle.shadow_min_mirrors=4",
            "lifecycle.max_ece=0.3",
        ],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    log_lines: list[str] = []
    pump = threading.Thread(
        target=lambda: log_lines.extend(iter(server.stdout.readline, "")),
        daemon=True,
    )
    pump.start()

    counts = {"alpha": {"ok": 0, "bad": 0}, "beta": {"ok": 0, "bad": 0}}
    bad_detail: list = []
    phase = {"drift": False}
    stop = threading.Event()

    def hammer(tenant: str) -> None:
        req_url = f"http://127.0.0.1:{port}/predict"
        while not stop.is_set():
            body = (
                drifted_body
                if tenant == "alpha" and phase["drift"]
                else normal_body
            )
            req = urllib.request.Request(
                req_url, data=body,
                headers={
                    "content-type": "application/json",
                    "x-tenant": tenant,
                },
            )
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    status = resp.status
                    resp.read()
            except urllib.error.HTTPError as err:
                status = err.code
                err.read()
            except (urllib.error.URLError, OSError) as err:
                counts[tenant]["bad"] += 1
                bad_detail.append((tenant, repr(err)))
                continue
            if status == 200:
                counts[tenant]["ok"] += 1
            else:
                counts[tenant]["bad"] += 1
                bad_detail.append((tenant, status))

    try:
        print("# tenancy-smoke: waiting for readiness", flush=True)
        deadline = time.time() + 600
        ready = False
        while time.time() < deadline and not ready:
            if server.poll() is not None:
                print("\n".join(log_lines[-50:]))
                raise SystemExit("server died before readiness")
            try:
                status, _ = get(f"http://127.0.0.1:{port}/healthz/ready", 5)
                ready = status == 200
            except (urllib.error.URLError, OSError, urllib.error.HTTPError):
                pass
            if not ready:
                time.sleep(1.0)
        if not ready:
            raise SystemExit("server never became ready")
        # Architecture-identical tenants share compiled entries: the
        # registry logs the adoption at warmup.
        assert any(
            "shares compiled entries" in line for line in log_lines
        ), "no shared-exec adoption logged for the twin tenants"
        print("# tenancy-smoke: shared-exec adoption logged", flush=True)

        clients = [
            threading.Thread(target=hammer, args=(t,), daemon=True)
            for t in ("alpha", "beta")
        ]
        for client in clients:
            client.start()
        time.sleep(2.0)  # phase 1: normal traffic on both tenants

        status, body = get(f"http://127.0.0.1:{port}/metrics", 30)
        text = body.decode()
        assert status == 200
        for tenant in ("alpha", "beta"):
            gen = metric_value(
                text, "mlops_tpu_bundle_generation", f'tenant="{tenant}"'
            )
            assert gen == 1.0, (tenant, gen)
            trig = metric_value(
                text, "mlops_tpu_drift_trigger_total", f'tenant="{tenant}"'
            )
            assert (trig or 0) == 0, (tenant, trig)

        print("# tenancy-smoke: drifting ALPHA's traffic only", flush=True)
        phase["drift"] = True

        def wait_metric(name: str, labels: str, minimum: float, budget: float):
            deadline = time.time() + budget
            while time.time() < deadline:
                if server.poll() is not None:
                    print("\n".join(log_lines[-80:]))
                    raise SystemExit("server died mid-loop")
                _, body = get(f"http://127.0.0.1:{port}/metrics", 30)
                value = metric_value(body.decode(), name, labels)
                if value is not None and value >= minimum:
                    return value
                time.sleep(0.5)
            print("\n".join(log_lines[-80:]))
            raise SystemExit(f"{name}{{{labels}}} never reached {minimum}")

        wait_metric(
            "mlops_tpu_drift_trigger_total", 'tenant="alpha"', 1, 120
        )
        print("# tenancy-smoke: alpha auto-retrain fired", flush=True)
        wait_metric(
            "mlops_tpu_promotions_total",
            'tenant="alpha",outcome="promoted"', 1, 300,
        )
        generation = wait_metric(
            "mlops_tpu_bundle_generation", 'tenant="alpha"', 2, 60
        )
        print(
            f"# tenancy-smoke: alpha hot swap landed (generation "
            f"{generation:g})",
            flush=True,
        )
        time.sleep(1.0)  # post-swap traffic on both tenants

        # ISOLATION: beta's loop never moved while alpha's completed.
        _, body = get(f"http://127.0.0.1:{port}/metrics", 30)
        text = body.decode()
        beta_gen = metric_value(
            text, "mlops_tpu_bundle_generation", 'tenant="beta"'
        )
        assert beta_gen == 1.0, (
            f"beta's bundle generation moved to {beta_gen} — per-tenant "
            "lifecycle isolation broken"
        )
        beta_trig = metric_value(
            text, "mlops_tpu_drift_trigger_total", 'tenant="beta"'
        )
        assert (beta_trig or 0) == 0, (
            f"beta drift triggers {beta_trig} — alpha's drifted window "
            "leaked into beta's monitor"
        )

        stop.set()
        for client in clients:
            client.join(timeout=60)
        for tenant in ("alpha", "beta"):
            assert counts[tenant]["ok"] > 0, (
                f"{tenant} hammer never completed a request"
            )
        assert counts["beta"]["bad"] == 0, (
            f"non-200s on the UNDRIFTED tenant: {counts['beta']['bad']} "
            f"(first: {bad_detail[:5]})"
        )
        assert counts["alpha"]["bad"] == 0, (
            f"non-200s on alpha during its own swap: "
            f"{counts['alpha']['bad']} (first: {bad_detail[:5]})"
        )
        print(
            f"# tenancy-smoke: alpha {counts['alpha']['ok']} / beta "
            f"{counts['beta']['ok']} requests, zero non-200 on both "
            "tenants across alpha's trigger/retrain/shadow/swap; draining",
            flush=True,
        )

        server.send_signal(signal.SIGTERM)
        rc = server.wait(timeout=90)
        pump.join(timeout=10)
        log = "\n".join(log_lines)
        assert rc == 0, f"server exited {rc}"
        assert "Task was destroyed" not in log, log[-2000:]
        print("# tenancy-smoke: OK (clean drain)", flush=True)
        return 0
    finally:
        stop.set()
        if server.poll() is None:
            server.kill()
            server.wait(timeout=10)


if __name__ == "__main__":
    sys.exit(main())
