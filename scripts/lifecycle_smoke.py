"""CI live-server lifecycle smoke: train -> serve (loop enabled) ->
inject drifted traffic -> assert auto-retrain fires -> assert gated hot
swap with ZERO non-200 responses during the window.

The end-to-end proof that the closed loop works as DEPLOYED (real CLI,
real process, real HTTP), not just under the in-process test harness:

1. synthesize a labeled DRIFTED window (numerics x10, labels preserved)
   — the out-of-band ground-truth delivery the retrain reads,
2. train a tiny bundle through the real CLI,
3. launch `mlops-tpu serve` single-process with ``lifecycle.enabled=true``
   and tight loop knobs,
4. hammer /predict continuously from a background thread, counting every
   non-200 — the bit-stable/zero-downtime assertion rides this counter,
5. phase 2: the traffic turns DRIFTED (8-row bodies so the K-S window is
   decisive); poll /metrics until ``mlops_tpu_drift_trigger_total`` >= 1
   (auto-retrain fired) and then until ``mlops_tpu_bundle_generation``
   >= 2 with ``mlops_tpu_promotions_total{outcome="promoted"}`` >= 1
   (shadow-gated hot swap landed),
6. assert the hammer saw zero non-200s across the whole window —
   trigger, retrain, mirroring, and the swap included,
7. SIGTERM and assert a clean drain (exit 0, no leaked tasks).

Run from the repo root: `python scripts/lifecycle_smoke.py` (CI pins
JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def get(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def metric_value(text: str, name: str, labels: str = "") -> float | None:
    pattern = re.escape(name + ("{" + labels + "}" if labels else "")) + r" ([-0-9.e+]+)"
    match = re.search(pattern, text)
    return float(match.group(1)) if match else None


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="lifecycle-smoke-")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")

    # 1. Labeled drifted window + request bodies (schema imports are
    # cheap and jax-free via the data layer).
    sys.path.insert(0, REPO)
    from mlops_tpu.data import generate_synthetic, write_csv_columns
    from mlops_tpu.schema import SCHEMA

    columns, labels = generate_synthetic(1500, seed=3)
    drifted = {k: list(v) for k, v in columns.items()}
    for feat in SCHEMA.numeric:
        drifted[feat.name] = [v * 10.0 for v in drifted[feat.name]]
    labeled_csv = f"{tmp}/labeled.csv"
    write_csv_columns(labeled_csv, drifted, labels)

    def records(cols, n, offset=0):
        names = [f.name for f in SCHEMA.categorical] + [
            f.name for f in SCHEMA.numeric
        ]
        return [
            {name: cols[name][offset + i] for name in names} for i in range(n)
        ]

    normal_body = json.dumps(records(columns, 8)).encode()
    drifted_body = json.dumps(records(drifted, 8, offset=16)).encode()

    print("# lifecycle-smoke: training tiny bundle", flush=True)
    train = subprocess.run(
        [
            sys.executable, "-m", "mlops_tpu", "train",
            "data.rows=3000",
            "model.hidden_dims=32,32", "model.embed_dim=4",
            "train.steps=100", "train.eval_every=100",
            "train.batch_size=256",
            f"registry.root={tmp}/registry", f"registry.run_root={tmp}/runs",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    if train.returncode != 0:
        print(train.stdout[-2000:], train.stderr[-2000:], sep="\n")
        raise SystemExit("train failed")
    bundle = json.loads(train.stdout.strip().splitlines()[-1])["bundle"]

    port = free_port()
    server = subprocess.Popen(
        [
            sys.executable, "-m", "mlops_tpu", "serve",
            "serve.host=127.0.0.1", f"serve.port={port}",
            f"serve.model_directory={bundle}",
            "serve.warmup_batch_sizes=1,8", "serve.max_batch=8",
            "serve.batch_window_ms=0",  # solo path: deterministic latency
            "serve.monitor_fetch_every_s=0.5",
            "lifecycle.enabled=true",
            f"lifecycle.dir={tmp}/lifecycle",
            f"lifecycle.labeled_path={labeled_csv}",
            "lifecycle.retrain_steps=50",
            "lifecycle.min_labeled_rows=500",
            "lifecycle.min_window_rows=32",
            "lifecycle.hysteresis_windows=2",
            "lifecycle.cooldown_s=2",
            "lifecycle.tick_s=0.25",
            "lifecycle.mirror_fraction=1.0",
            "lifecycle.shadow_min_mirrors=4",
            "lifecycle.max_ece=0.3",
        ],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    log_lines: list[str] = []
    pump = threading.Thread(
        target=lambda: log_lines.extend(iter(server.stdout.readline, "")),
        daemon=True,
    )
    pump.start()

    counts = {"ok": 0, "bad": 0}
    bad_detail: list = []
    phase = {"drift": False}
    stop = threading.Event()

    def hammer() -> None:
        req_url = f"http://127.0.0.1:{port}/predict"
        while not stop.is_set():
            body = drifted_body if phase["drift"] else normal_body
            req = urllib.request.Request(
                req_url, data=body,
                headers={"content-type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    status = resp.status
                    resp.read()
            except urllib.error.HTTPError as err:
                status = err.code
                err.read()
            except (urllib.error.URLError, OSError) as err:
                counts["bad"] += 1
                bad_detail.append(repr(err))
                continue
            if status == 200:
                counts["ok"] += 1
            else:
                counts["bad"] += 1
                bad_detail.append(status)

    try:
        print("# lifecycle-smoke: waiting for readiness", flush=True)
        deadline = time.time() + 600
        ready = False
        while time.time() < deadline and not ready:
            if server.poll() is not None:
                print("\n".join(log_lines[-50:]))
                raise SystemExit("server died before readiness")
            try:
                status, _ = get(f"http://127.0.0.1:{port}/healthz/ready", 5)
                ready = status == 200
            except (urllib.error.URLError, OSError, urllib.error.HTTPError):
                pass
            if not ready:
                time.sleep(1.0)
        if not ready:
            raise SystemExit("server never became ready")

        client = threading.Thread(target=hammer, daemon=True)
        client.start()
        time.sleep(2.0)  # phase 1: normal traffic, no trigger expected

        status, body = get(f"http://127.0.0.1:{port}/metrics", 30)
        text = body.decode()
        assert status == 200
        assert metric_value(
            text, "mlops_tpu_bundle_generation", 'tenant="default"'
        ) == 1.0
        assert (metric_value(
            text, "mlops_tpu_drift_trigger_total", 'tenant="default"'
        ) or 0) == 0

        print("# lifecycle-smoke: injecting drifted traffic", flush=True)
        phase["drift"] = True

        def wait_metric(name: str, labels: str, minimum: float, budget: float):
            deadline = time.time() + budget
            while time.time() < deadline:
                if server.poll() is not None:
                    print("\n".join(log_lines[-80:]))
                    raise SystemExit("server died mid-loop")
                _, body = get(f"http://127.0.0.1:{port}/metrics", 30)
                value = metric_value(body.decode(), name, labels)
                if value is not None and value >= minimum:
                    return value
                time.sleep(0.5)
            print("\n".join(log_lines[-80:]))
            raise SystemExit(f"{name}{{{labels}}} never reached {minimum}")

        wait_metric(
            "mlops_tpu_drift_trigger_total", 'tenant="default"', 1, 120
        )
        print("# lifecycle-smoke: auto-retrain fired", flush=True)
        wait_metric(
            "mlops_tpu_promotions_total",
            'tenant="default",outcome="promoted"', 1, 300
        )
        generation = wait_metric(
            "mlops_tpu_bundle_generation", 'tenant="default"', 2, 60
        )
        print(
            f"# lifecycle-smoke: hot swap landed (generation {generation:g})",
            flush=True,
        )
        time.sleep(1.0)  # post-swap traffic on the promoted bundle
        stop.set()
        client.join(timeout=60)
        assert counts["ok"] > 0, "hammer never completed a request"
        assert counts["bad"] == 0, (
            f"non-200s during the lifecycle window: {counts['bad']} "
            f"(first: {bad_detail[:5]}) — the swap was not zero-downtime"
        )
        print(
            f"# lifecycle-smoke: {counts['ok']} requests, zero non-200 "
            "across trigger/retrain/shadow/swap; draining", flush=True,
        )

        server.send_signal(signal.SIGTERM)
        rc = server.wait(timeout=90)
        pump.join(timeout=10)
        log = "\n".join(log_lines)
        assert rc == 0, f"server exited {rc}"
        assert "Task was destroyed" not in log, log[-2000:]
        print("# lifecycle-smoke: OK (clean drain)", flush=True)
        return 0
    finally:
        stop.set()
        if server.poll() is None:
            server.kill()
            server.wait(timeout=10)


if __name__ == "__main__":
    sys.exit(main())
