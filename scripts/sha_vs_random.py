"""Equal-budget HPO comparison: successive halving vs random search.

VERDICT r4 #6's "done" evidence: at the SAME total step budget
(trials x steps), SHA should select a better (or equal) validation AUC
than random search, because it reallocates most of the budget to the
candidates that earn it. One JSON line:

    JAX_PLATFORMS=cpu python scripts/sha_vs_random.py

Knobs: SWEEP_TRIALS (default 16), SWEEP_STEPS (default 300), SEEDS
(default 3 comma-separated sweep seeds).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mlops_tpu.commands import _honor_jax_platforms_env  # noqa: E402

_honor_jax_platforms_env()

import numpy as np  # noqa: E402

from mlops_tpu.config import HPOConfig, ModelConfig, TrainConfig  # noqa: E402
from mlops_tpu.data import Preprocessor, generate_synthetic  # noqa: E402
from mlops_tpu.train.hpo import run_hpo  # noqa: E402


def main() -> None:
    trials = int(os.environ.get("SWEEP_TRIALS", "16"))
    steps = int(os.environ.get("SWEEP_STEPS", "300"))
    seeds = [
        int(s) for s in os.environ.get("SEEDS", "11,12,13").split(",")
    ]
    columns, labels = generate_synthetic(30_000, seed=5)
    prep = Preprocessor.fit(columns)
    ds = prep.encode(columns, labels)
    idx = np.arange(ds.n)
    train_ds, valid_ds = ds.slice(idx[:24_000]), ds.slice(idx[24_000:])

    model = ModelConfig(family="mlp", hidden_dims=(128, 64), precision="f32")
    tconfig = TrainConfig(batch_size=512)
    rows = {"random": [], "sha": []}
    wall = {"random": 0.0, "sha": 0.0}
    for seed in seeds:
        for strategy in ("random", "sha"):
            hconfig = HPOConfig(
                trials=trials,
                steps=steps,
                seed=seed,
                strategy=strategy,
                eta=2,
                sha_rungs=3,
            )
            t0 = time.perf_counter()
            res = run_hpo(
                model,
                dataclasses.replace(tconfig),
                hconfig,
                train_ds,
                valid_ds,
            )
            wall[strategy] += time.perf_counter() - t0
            rows[strategy].append(
                res.best_metrics["validation_roc_auc_score"]
            )
    budget = trials * steps
    # ACTUAL sha spend, not a re-derivation of run_sha's plan: each trial
    # record carries the steps it had trained when it was eliminated (or
    # finished), so the sum is what the sweep really spent.
    sha_budget = sum(t["steps"] for t in res.trials)
    print(
        json.dumps(
            {
                "metric": "sha_vs_random_auc_delta",
                "value": round(
                    float(np.mean(rows["sha"]) - np.mean(rows["random"])), 5
                ),
                "unit": "auc",
                "budget_steps_random": budget,
                "budget_steps_sha": sha_budget,
                "auc_random": [round(float(v), 5) for v in rows["random"]],
                "auc_sha": [round(float(v), 5) for v in rows["sha"]],
                "wall_s_random": round(wall["random"], 1),
                "wall_s_sha": round(wall["sha"], 1),
                "seeds": seeds,
            }
        )
    )


if __name__ == "__main__":
    main()
