"""CI replica-set smoke: a live 2-replica plane survives a replica kill.

The deployed-shape proof for the engine replica set (ISSUE 13,
mlops_tpu/replicaset/) — real CLI, real processes, real signals:

1. train a tiny bundle through the real CLI,
2. launch `mlops-tpu serve --workers 2 --replicas 2` (SO_REUSEPORT front
   ends + the shared-memory ring + TWO supervised engine replicas, both
   warmed from one AOT cache) with two simulated devices
   (``XLA_FLAGS=--xla_force_host_platform_device_count=2``),
3. hammer /predict with a fixed payload whose response is known, then
   kill -9 engine replica 1 MID-TRAFFIC,
4. assert ZERO WRONG RESPONSES: every 200 body is bit-identical to the
   pre-kill reference (a cross-wired slab or double-served completion
   would show here), and every non-200 is inside the documented
   brownout contract (503/504),
5. assert the SURVIVOR KEEPS SERVING: requests that started AND
   finished inside the outage window still answer 200 (the router
   routes around the hole — a partial outage is 1/E capacity, not
   unreadiness),
6. assert the RESPAWN REJOINS: replica 1's ready word returns, its
   incarnation bumps to 2, its respawn counter reads 1, and every
   per-replica ``*_total`` counter is MONOTONE across the whole drill,
7. SIGTERM and assert a clean drain (exit 0, the drain log line).

Run from the repo root: `python scripts/replica_smoke.py` (CI pins
JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def get(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def replica_series(text: str) -> dict[str, float]:
    """Every per-replica sample keyed by full series name+labels."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("mlops_tpu_replica_"):
            name, _, value = line.rpartition(" ")
            try:
                out[name] = float(value)
            except ValueError:
                pass
    return out


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="replica-smoke-")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # Two SIMULATED devices for the two replicas (flag must precede any
    # jax import in the children, which the CLI guarantees — jax loads
    # after the fork).
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()

    print("# replica-smoke: training tiny bundle", flush=True)
    train = subprocess.run(
        [
            sys.executable, "-m", "mlops_tpu", "train",
            "data.rows=3000",
            "model.hidden_dims=32,32", "model.embed_dim=4",
            "train.steps=100", "train.eval_every=100",
            "train.batch_size=256",
            f"registry.root={tmp}/registry", f"registry.run_root={tmp}/runs",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    if train.returncode != 0:
        print(train.stdout[-2000:], train.stderr[-2000:], sep="\n")
        raise SystemExit("train failed")
    bundle = json.loads(train.stdout.strip().splitlines()[-1])["bundle"]
    print(f"# replica-smoke: bundle at {bundle}", flush=True)

    port = free_port()
    server = subprocess.Popen(
        [
            sys.executable, "-m", "mlops_tpu", "serve",
            "--workers", "2", "--replicas", "2",
            "serve.host=127.0.0.1", f"serve.port={port}",
            f"serve.model_directory={bundle}",
            "serve.warmup_batch_sizes=1,8", "serve.max_batch=8",
            "serve.request_timeout_s=120",
            f"cache.dir={tmp}/cache",
            "serve.drain_deadline_s=8", "serve.zygote_join_deadline_s=10",
            "serve.engine_zygote_join_s=16",
        ],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    log_lines: list[str] = []
    pump = threading.Thread(
        target=lambda: log_lines.extend(iter(server.stdout.readline, "")),
        daemon=True,
    )
    pump.start()

    body = json.dumps([{"credit_limit": 12000, "age": 34}]).encode()

    def predict(timeout: float = 120.0, deadline_ms: int = 90000):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict",
            data=body,
            headers={
                "content-type": "application/json",
                "x-request-deadline-ms": str(deadline_ms),
            },
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())

    try:
        print("# replica-smoke: waiting for readiness", flush=True)
        deadline = time.time() + 600
        ready = False
        while time.time() < deadline and not ready:
            if server.poll() is not None:
                print("\n".join(log_lines[-50:]))
                raise SystemExit("server died before readiness")
            try:
                status, _ = get(f"http://127.0.0.1:{port}/healthz/ready", 5)
                ready = status == 200
            except (urllib.error.URLError, OSError):
                pass
            if not ready:
                time.sleep(1.0)
        if not ready:
            raise SystemExit("server never became ready")

        status, expected = predict()
        assert status == 200
        print("# replica-smoke: reference response captured", flush=True)

        # /healthz/ready answers on the FIRST warm replica; the
        # supervisor staggers the siblings (replica 0 populates the AOT
        # cache, the rest deserialize) — wait for the whole fleet.
        baseline = None
        deadline = time.time() + 300
        while time.time() < deadline and baseline is None:
            status, text = get(f"http://127.0.0.1:{port}/metrics", 30)
            series = replica_series(text.decode())
            if (
                series.get('mlops_tpu_replica_ready{replica="0"}') == 1.0
                and series.get('mlops_tpu_replica_ready{replica="1"}') == 1.0
            ):
                baseline = series
            else:
                time.sleep(0.5)
        assert baseline is not None, "replica 1 never became ready"
        print("# replica-smoke: both replicas ready", flush=True)

        # ---- hammer + kill -9 replica 1 mid-traffic ------------------
        results: list[tuple[float, float, int, bool]] = []
        lock = threading.Lock()
        stop = threading.Event()

        def hammer() -> None:
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    st, payload = predict()
                    right = payload == expected
                except urllib.error.HTTPError as err:
                    st, right = err.code, True  # non-200: contract below
                except (urllib.error.URLError, OSError):
                    continue  # severed connection: retried, not a verdict
                with lock:
                    results.append(
                        (t0, time.perf_counter() - t0, st, right)
                    )

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        # 3 s of steady state: the telemetry cadence (2 s) ticks at
        # least once, so replica 0's rows-scored row is nonzero before
        # the kill. Replica 0 is the KILL TARGET on purpose — at this
        # low concurrency the router's small-class affinity keeps the
        # whole tenant's traffic on its sticky replica (0, the
        # deterministic first pick), so killing 0 is the interesting
        # drill: the router must fail over to 1, 0's busy slots must
        # park and replay, and the respawn must rejoin.
        time.sleep(3.0)
        pid_line = next(
            line for line in log_lines
            if re.search(r"engine replica 0 started \(pid \d+\)", line)
        )
        replica0_pid = int(re.search(r"pid (\d+)", pid_line).group(1))
        kill_t = time.perf_counter()
        os.kill(replica0_pid, signal.SIGKILL)
        print(f"# replica-smoke: killed replica 0 (pid {replica0_pid})",
              flush=True)

        # First wait for the supervisor to STAMP the outage (replica
        # 0's ready word down): a probe issued before the stamp would
        # route to the dead replica and park — the hammer threads
        # already cover that path; the failover evidence needs fresh
        # admissions issued while the router can see the hole.
        stamped = False
        deadline = time.time() + 60
        while time.time() < deadline and not stamped:
            time.sleep(0.1)
            try:
                _, text = get(f"http://127.0.0.1:{port}/metrics", 10)
            except (urllib.error.URLError, OSError):
                continue
            series = replica_series(text.decode())
            stamped = (
                series.get('mlops_tpu_replica_ready{replica="0"}') == 0.0
            )
        assert stamped, "supervisor never stamped replica 0's outage"
        outage_stamped_t = time.perf_counter()

        # Rejoin = replica 0's ready word back up on /metrics. While
        # waiting, PROBE with fresh short-deadline requests from this
        # thread: the router must send them to the survivor (the only
        # ready replica), so they answer fast 200s THROUGH the outage —
        # a probe that somehow parked 504s at its own 5 s budget
        # instead of wedging the loop.
        rejoin_t = None
        deadline = time.time() + 300
        while time.time() < deadline and rejoin_t is None:
            t0 = time.perf_counter()
            try:
                st, payload = predict(timeout=10, deadline_ms=5000)
                with lock:
                    results.append(
                        (t0, time.perf_counter() - t0, st,
                         payload == expected)
                    )
            except urllib.error.HTTPError as err:
                with lock:
                    results.append(
                        (t0, time.perf_counter() - t0, err.code, True)
                    )
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.1)
            try:
                _, text = get(f"http://127.0.0.1:{port}/metrics", 10)
            except (urllib.error.URLError, OSError):
                continue
            series = replica_series(text.decode())
            if series.get('mlops_tpu_replica_ready{replica="0"}') == 1.0:
                rejoin_t = time.perf_counter()
        assert rejoin_t is not None, "replica 0 never rejoined"
        time.sleep(2.0)  # post-rejoin tail under traffic
        stop.set()
        for t in threads:
            t.join(timeout=120)

        with lock:
            snapshot = list(results)
        # ZERO WRONG RESPONSES: every 200 is bit-identical to the
        # reference; everything else stays inside the brownout contract.
        wrong = [s for s in snapshot if s[2] == 200 and not s[3]]
        assert not wrong, f"{len(wrong)} wrong 200 bodies"
        illegal = {s[2] for s in snapshot} - {200, 503, 504}
        assert not illegal, f"statuses outside the contract: {illegal}"
        # SURVIVOR KEEPS SERVING: 200s that started AND finished inside
        # the outage window (the router failing over to replica 1).
        during = [
            s for s in snapshot
            if s[2] == 200
            and s[0] > outage_stamped_t
            and s[0] + s[1] < rejoin_t
        ]
        assert during, "no 200s served during the outage window"
        print(
            f"# replica-smoke: {len(during)} requests served by the "
            f"survivor during the {rejoin_t - kill_t:.1f}s outage",
            flush=True,
        )

        # RESPAWN REJOINS with monotone per-replica counters.
        _, text = get(f"http://127.0.0.1:{port}/metrics", 30)
        final = replica_series(text.decode())
        assert final.get('mlops_tpu_replica_incarnation{replica="0"}') == 2.0
        assert final.get('mlops_tpu_replica_respawn_total{replica="0"}') == 1.0
        assert final.get('mlops_tpu_replica_respawn_total{replica="1"}') == 0.0
        regressions = [
            name for name, value in baseline.items()
            if "_total" in name and final.get(name, 0.0) < value
        ]
        assert not regressions, f"non-monotone replica counters: {regressions}"
        both_rows = [
            final.get(
                f'mlops_tpu_replica_rows_scored_total{{replica="{r}"}}', 0.0
            )
            for r in (0, 1)
        ]
        assert all(v > 0 for v in both_rows), both_rows
        print("# replica-smoke: counters monotone, both replicas scoring; "
              "draining", flush=True)

        server.send_signal(signal.SIGTERM)
        rc = server.wait(timeout=120)
        pump.join(timeout=10)
        log = "\n".join(log_lines)
        assert rc == 0, f"server exited {rc}\n" + log[-2000:]
        assert "drained" in log, log[-2000:]
        print("# replica-smoke: OK (clean drain)", flush=True)
        return 0
    finally:
        if server.poll() is None:
            server.kill()
            server.wait(timeout=10)


if __name__ == "__main__":
    sys.exit(main())
