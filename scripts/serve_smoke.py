"""CI live-server smoke: train -> `serve --workers 2` -> predict -> drain.

The end-to-end proof that the multi-worker plane works as DEPLOYED (real
CLI, real processes, real signals), not just under the in-process test
harness:

1. train a tiny bundle through the real CLI,
2. launch `mlops-tpu serve --workers 2` (SO_REUSEPORT front ends + the
   shared-memory ring) as a subprocess,
3. wait for /healthz/ready (engine warmup),
4. fire concurrent predicts from two separate connections and validate
   the response contract (identical bodies -> identical responses),
5. scrape /metrics and assert BOTH workers are present (ring gauges are
   emitted per worker unconditionally) plus the request counters,
6. kill -9 one front end and assert the supervisor respawns it (the
   supervisor parent never loads a backend — replacements never fork
   from the engine's threaded world) and the plane keeps serving,
7. SIGTERM the server and assert a clean drain: exit code 0, the drain
   log line, and zero leaked-task warnings.

Run from the repo root: `python scripts/serve_smoke.py` (CI pins
JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RECORD = {"credit_limit": 12000, "age": 34}


def free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def get(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def post_predict(
    port: int, results: list, idx: int, request_id: str | None = None
) -> None:
    body = json.dumps([RECORD]).encode()
    headers = {"content-type": "application/json"}
    if request_id:
        headers["x-request-id"] = request_id
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict", data=body, headers=headers
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        results[idx] = (
            resp.status,
            json.loads(resp.read()),
            resp.headers.get("x-request-id"),
        )


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="serve-smoke-")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")

    print("# serve-smoke: training tiny bundle", flush=True)
    train = subprocess.run(
        [
            sys.executable, "-m", "mlops_tpu", "train",
            "data.rows=3000",
            "model.hidden_dims=32,32", "model.embed_dim=4",
            "train.steps=100", "train.eval_every=100",
            "train.batch_size=256",
            f"registry.root={tmp}/registry", f"registry.run_root={tmp}/runs",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    if train.returncode != 0:
        print(train.stdout[-2000:], train.stderr[-2000:], sep="\n")
        raise SystemExit("train failed")
    bundle = json.loads(train.stdout.strip().splitlines()[-1])["bundle"]
    print(f"# serve-smoke: bundle at {bundle}", flush=True)

    port = free_port()
    trace_dir = os.path.join(tmp, "traces")
    flightrec_dir = os.path.join(tmp, "flightrec")
    server = subprocess.Popen(
        [
            sys.executable, "-m", "mlops_tpu", "serve", "--workers", "2",
            "serve.host=127.0.0.1", f"serve.port={port}",
            f"serve.model_directory={bundle}",
            "serve.warmup_batch_sizes=1,8", "serve.max_batch=8",
            "trace.enabled=true", f"trace.dir={trace_dir}",
            # sloscope armed: the clean-run contract is ZERO alerts
            # fired and ZERO flight-recorder dumps written across the
            # whole smoke (ISSUE 14). Availability keeps production
            # thresholds; the latency threshold widens to a CI-box
            # bound (a loaded runner's first-request latency must not
            # flake the zero-alert assertion — latency SLOs are tuned
            # per deployment, availability is the invariant here).
            "slo.enabled=true", "slo.latency_threshold_ms=250",
            f"slo.flightrec_dir={flightrec_dir}",
        ],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    log_lines: list[str] = []
    pump = threading.Thread(
        target=lambda: log_lines.extend(iter(server.stdout.readline, "")),
        daemon=True,
    )
    pump.start()
    try:
        print("# serve-smoke: waiting for readiness", flush=True)
        deadline = time.time() + 600
        ready = False
        while time.time() < deadline and not ready:
            if server.poll() is not None:
                print("\n".join(log_lines[-50:]))
                raise SystemExit("server died before readiness")
            try:
                status, _ = get(f"http://127.0.0.1:{port}/healthz/ready", 5)
                ready = status == 200
            except (urllib.error.URLError, OSError, urllib.error.HTTPError):
                pass
            if not ready:
                time.sleep(1.0)
        if not ready:
            raise SystemExit("server never became ready")
        print("# serve-smoke: ready; concurrent predicts", flush=True)

        results: list = [None, None]
        threads = [
            threading.Thread(
                target=post_predict,
                args=(port, results, i, f"smoke-trace-{i}"),
            )
            for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=90)
        for i, (status, payload, trace_id) in enumerate(results):
            assert status == 200, results
            assert set(payload) == {
                "predictions", "outliers", "feature_drift_batch"
            }, payload
            assert len(payload["predictions"]) == 1
            # tracewire: the inbound x-request-id echoes on the response.
            assert trace_id == f"smoke-trace-{i}", results
        # Identical requests -> identical responses across connections
        # (and therefore across whichever workers served them).
        assert results[0][1] == results[1][1], results
        print("# serve-smoke: trace ids echoed on both predicts", flush=True)

        status, body = get(f"http://127.0.0.1:{port}/metrics", 30)
        text = body.decode()
        assert status == 200
        for worker in (0, 1):
            needle = (
                f'mlops_tpu_ring_depth{{worker="{worker}",class="small",'
                'tenant="default"}'
            )
            assert needle in text, f"worker {worker} missing from /metrics"
        assert "mlops_tpu_requests_total" in text
        print("# serve-smoke: /metrics shows both workers", flush=True)

        # sloscope (ISSUE 14): the SLO/alert block is exported, the
        # build-info inventory gauge is present, and on a CLEAN plane
        # every alert_active sample is 0.
        assert "mlops_tpu_build_info{" in text
        assert 'mlops_tpu_slo_total{slo="availability"' in text
        alert_samples = [
            line for line in text.splitlines()
            if line.startswith("mlops_tpu_alert_active{")
        ]
        assert alert_samples, "alert_active series missing"
        firing = [line for line in alert_samples
                  if not line.endswith(" 0")]
        assert not firing, f"clean run fired alerts: {firing}"
        # /healthz verdict endpoint: a clean serving plane says "ok".
        status, body = get(f"http://127.0.0.1:{port}/healthz", 30)
        verdict = json.loads(body)
        assert status == 200 and verdict["verdict"] == "ok", verdict
        print("# serve-smoke: sloscope clean (zero alerts, verdict ok)",
              flush=True)

        # Kill -9 one front end: the supervisor (thread-free and
        # jax-free, so its forks never cross jax threads) must respawn
        # it and the plane must keep serving.
        spawn_line = next(line for line in log_lines if "spawned" in line)
        pids = [
            int(p) for p in
            re.findall(r"\d+", spawn_line.split("(pids", 1)[1])
        ]
        # SIGKILL discards the victim's un-flushed span buffer — a
        # DOCUMENTED bounded loss (<= trace.flush_interval_s, 0.5 s
        # default). Wait out one flush interval so the span assertion
        # after drain tests durable behavior, not this race.
        time.sleep(0.8)
        os.kill(pids[0], signal.SIGKILL)
        deadline = time.time() + 30
        while time.time() < deadline and not any(
            "respawning" in line for line in log_lines
        ):
            time.sleep(0.2)
        assert any("respawning" in line for line in log_lines), (
            "supervisor never respawned the killed front end"
        )
        deadline = time.time() + 30
        served = False
        while time.time() < deadline and not served:
            try:
                results = [None]
                post_predict(port, results, 0)
                served = results[0][0] == 200
            except (urllib.error.URLError, OSError):
                time.sleep(0.2)
        assert served, "plane stopped serving after front-end respawn"
        print("# serve-smoke: killed front end respawned by supervisor; "
              "draining", flush=True)

        server.send_signal(signal.SIGTERM)
        rc = server.wait(timeout=90)
        pump.join(timeout=10)
        log = "\n".join(log_lines)
        assert rc == 0, f"server exited {rc}"
        assert "drained" in log, log[-2000:]
        assert "Task was destroyed" not in log, log[-2000:]
        assert "Traceback" not in log, log[-4000:]
        # tracewire: the drain flushed each worker's span JSONL — every
        # line parses (no torn records) and the smoke's trace ids appear
        # as stitched ring-plane spans.
        span_files = [
            os.path.join(trace_dir, f)
            for f in os.listdir(trace_dir)
            if f.startswith("spans-w") and f.endswith(".jsonl")
        ]
        assert span_files, f"no span JSONL under {trace_dir}"
        spans = []
        for path in span_files:
            with open(path) as f:
                for line in f:
                    spans.append(json.loads(line))  # torn line -> raises
        smoke_ids = {
            s["trace_id"] for s in spans if s.get("kind") == "span"
        }
        assert {"smoke-trace-0", "smoke-trace-1"} <= smoke_ids, smoke_ids
        print(f"# serve-smoke: {len(spans)} spans parsed clean from "
              f"{len(span_files)} worker files", flush=True)
        # sloscope zero-dump contract: a clean run (even one that
        # SIGKILLed a front end and drained on SIGTERM) writes NO
        # flight-recorder dumps — dumps are anomaly evidence, not noise.
        dumps = (
            os.listdir(flightrec_dir)
            if os.path.isdir(flightrec_dir) else []
        )
        assert not dumps, f"clean run wrote flight-recorder dumps: {dumps}"
        print("# serve-smoke: zero flight-recorder dumps (clean plane)",
              flush=True)
        print("# serve-smoke: OK (clean drain, zero leaked tasks)",
              flush=True)
        return 0
    finally:
        if server.poll() is None:
            server.kill()
            server.wait(timeout=10)


if __name__ == "__main__":
    sys.exit(main())
